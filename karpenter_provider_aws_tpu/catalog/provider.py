"""CatalogProvider: the cached, seqnum-versioned view the solver consumes.

Reference parity: ``pkg/providers/instancetype/instancetype.go`` —
``DefaultProvider.List`` with a composite cache key of seqnums/hashes
(instancetype.go:121-139), 12h refresh, RWMutex-guarded snapshots
(instancetype.go:65-79), and ``createOfferings`` crossing types x zones x
capacity-types with the ICE mask (instancetype.go:252-293).

TPU-first addition: the provider also exports the problem *tensors* —
allocatable capacity matrix ``C[T, R]``, offering price/availability arrays
``price[T, Z, C]`` / ``avail[T, Z, C]`` (C = NUM_CAPACITY_TYPES) — which are what actually ship to
the device (SURVEY.md section 7.1-7.2).
"""

from __future__ import annotations

import math
import threading
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from ..models import labels as lbl
from ..models.resources import (
    CPU,
    MEMORY,
    NUM_RESOURCES,
    PODS,
    ResourceVector,
)
from ..utils.cache import CacheTTL, TTLCache
from ..utils.clock import Clock, RealClock
from ..utils.unavailable import UnavailableOfferings
from .instancetypes import DEFAULT_ZONES, InstanceType, generate_catalog
from .pricing import PricingProvider


@dataclass
class OverheadOptions:
    """Knobs for capacity -> allocatable (parity: options.go VMMemoryOverheadPercent
    + kubelet reserved/eviction defaults in types.go:354-416)."""

    vm_memory_overhead_percent: float = 0.075
    system_reserved_cpu_milli: float = 100.0
    system_reserved_memory_mib: float = 100.0
    eviction_threshold_memory_mib: float = 100.0
    max_pods: Optional[int] = None
    pods_per_core: Optional[int] = None
    reserved_enis: int = 0


def kube_reserved_cpu_milli(vcpus: int) -> float:
    """The kubelet CPU-reservation curve (parity: types.go:364-383):
    6% of the first core, 1% of the second, 0.5% of cores 3-4, 0.25% beyond."""
    cores = float(vcpus)
    reserved = 0.0
    tiers = [(1.0, 0.06), (1.0, 0.01), (2.0, 0.005), (math.inf, 0.0025)]
    for width, frac in tiers:
        take = min(cores, width)
        if take <= 0:
            break
        reserved += take * frac * 1000.0
        cores -= take
    return reserved


def kube_reserved_memory_mib(pods: float) -> float:
    """parity: types.go:389-401 — 255 MiB + 11 MiB per pod slot."""
    return 255.0 + 11.0 * pods


_provider_uid = __import__("itertools").count()


class CatalogProvider:
    def __init__(
        self,
        types: Optional[Sequence[InstanceType]] = None,
        pricing: Optional[PricingProvider] = None,
        unavailable: Optional[UnavailableOfferings] = None,
        overhead: Optional[OverheadOptions] = None,
        zones: Sequence[str] = DEFAULT_ZONES,
        clock: Optional[Clock] = None,
    ):
        self._clock = clock or RealClock()
        self._lock = threading.RLock()
        self.uid = next(_provider_uid)  # distinguishes caches across providers
        self._types: list[InstanceType] = list(types) if types is not None else generate_catalog(zones)
        self._index = {t.name: i for i, t in enumerate(self._types)}
        self.pricing = pricing or PricingProvider()
        self.unavailable = unavailable or UnavailableOfferings(clock=self._clock)
        self.overhead = overhead or OverheadOptions()
        from .reservations import ReservationStore

        self.reservations = ReservationStore()
        self.zones = tuple(zones)
        self._catalog_seq = 0
        self._tensor_cache = TTLCache(default_ttl=CacheTTL.INSTANCE_TYPES, clock=self._clock)

    # -- basic views -------------------------------------------------------
    def list(self) -> list[InstanceType]:
        with self._lock:
            return list(self._types)

    def get(self, name: str) -> Optional[InstanceType]:
        with self._lock:
            i = self._index.get(name)
            return self._types[i] if i is not None else None

    def names(self) -> list[str]:
        with self._lock:
            return [t.name for t in self._types]

    def __len__(self) -> int:
        return len(self._types)

    def refresh(self, types: Sequence[InstanceType]) -> None:
        """Swap in a new catalog snapshot (12h refresh controller path;
        parity: instancetype.go:181-250 UpdateInstanceTypes)."""
        with self._lock:
            self._types = list(types)
            self._index = {t.name: i for i, t in enumerate(self._types)}
            self._catalog_seq += 1
            self._tensor_cache.flush()

    # -- allocatable math --------------------------------------------------
    def allocatable(self, it: InstanceType, max_pods: Optional[int] = None,
                    ephemeral_gib: int = 20,
                    instance_store_policy: Optional[str] = None) -> ResourceVector:
        """capacity - VM overhead - kube/system reserved - eviction
        (parity: types.go:182-215 Allocatable). ``max_pods`` is the per-pool
        kubelet override, which wins over the global overhead option
        (parity: the kubelet maxPods input to types.go pods());
        ``ephemeral_gib``/``instance_store_policy`` come from the nodeclass
        (root block device size; RAID0 instance-store policy)."""
        o = self.overhead
        if max_pods is not None:
            pods = float(max_pods)
        elif o.max_pods is not None:
            pods = float(o.max_pods)
        else:
            pods = float(max(1, (it.max_enis - o.reserved_enis) * (it.ips_per_eni - 1) + 2))
            if o.pods_per_core:
                pods = min(pods, float(o.pods_per_core * it.vcpus))
        cap = it.capacity(max_pods=int(pods), ephemeral_gib=ephemeral_gib,
                          instance_store_policy=instance_store_policy)
        v = cap.v.copy()
        v[MEMORY] = v[MEMORY] * (1.0 - o.vm_memory_overhead_percent)
        v[MEMORY] -= kube_reserved_memory_mib(pods) + o.system_reserved_memory_mib + o.eviction_threshold_memory_mib
        v[CPU] -= kube_reserved_cpu_milli(it.vcpus) + o.system_reserved_cpu_milli
        v = np.maximum(v, 0.0)
        return ResourceVector(v)

    # -- seqnum composite key (parity: instancetype.go:121-139) ------------
    def cache_key(self) -> tuple:
        return (
            self._catalog_seq,
            self.pricing.seq_num(),
            self.unavailable.seq_num(),
            self.reservations.seq_num(),
            self.overhead.vm_memory_overhead_percent,
            self.overhead.max_pods,
        ) + self._market_fragment()

    def _market_fragment(self) -> tuple:
        """The clock-driven part of the cache key. Everything slot- or
        price-shaped already rides the seqnums above; only two things move
        with the clock alone — the MarketModel tick (reclaim discounts are
        a function of it) and bounded-window open/close transitions. Empty
        () when the market is off or there is no market state, so the key
        is the exact pre-market tuple and cached tensors keep hitting."""
        from ..market import (
            market_enabled,
            windows_cache_key,
            windows_from_reservations,
        )

        if not market_enabled():
            return ()
        frag: list = []
        now = self._clock.now()
        model = self.pricing.market
        if model is not None:
            frag.append(("tick", model.tick_index(now)))
        wkey = windows_cache_key(
            windows_from_reservations(self.reservations.list()), now
        )
        if wkey:
            frag.append(("win", wkey))
        return tuple(frag)

    # -- tensor exports (the TPU-facing view) ------------------------------
    def tensors(self) -> "CatalogTensors":
        # NOTE: never hold the cache lock while building (the build takes the
        # provider lock; refresh() takes provider-then-cache — get_or_load
        # here would invert the order and deadlock). A racy double-build is
        # benign: both snapshots are identical for the same key.
        key = ("tensors", self.cache_key())
        hit = self._tensor_cache.get(key)
        if hit is not None:
            return hit
        built = self._build_tensors()
        self._tensor_cache.set(key, built)
        return built

    def _build_tensors(self) -> "CatalogTensors":
        from ..market import (
            apply_window_columns,
            market_enabled,
            windows_from_reservations,
        )

        with self._lock:
            T, Z = len(self._types), len(self.zones)
            zone_idx = {z: i for i, z in enumerate(self.zones)}
            C = np.zeros((T, NUM_RESOURCES), dtype=np.float32)
            price = np.full((T, Z, lbl.NUM_CAPACITY_TYPES), np.inf, dtype=np.float32)
            avail = np.zeros((T, Z, lbl.NUM_CAPACITY_TYPES), dtype=bool)
            names = tuple(t.name for t in self._types)
            use_market = market_enabled()
            model = self.pricing.market if use_market else None
            now = self._clock.now()
            for ti, it in enumerate(self._types):
                C[ti] = self.allocatable(it).v
                for o in it.offerings:
                    zi = zone_idx.get(o.zone)
                    if zi is None:
                        continue
                    if o.capacity_type not in lbl.CAPACITY_TYPES:
                        continue  # unknown market (future data): degrade, don't crash
                    ci = lbl.CAPACITY_TYPES.index(o.capacity_type)
                    live = o.available and not self.unavailable.is_unavailable(
                        it.name, o.zone, o.capacity_type
                    )
                    # live price source wins over the snapshot on the offering
                    p = (
                        self.pricing.on_demand_price(it)
                        if ci == 0
                        else self.pricing.spot_price(it, o.zone)
                    )
                    if model is not None and ci == lbl.SPOT_INDEX:
                        # reclaim-risk premium, folded into the price VALUE
                        # so every consumer (FFD sort, consolidation screen,
                        # optimizer LP objective) arbitrages the same
                        # effective spot — and no jit signature changes
                        p = p * (1.0 + model.reclaim_lambda
                                 * model.reclaim_probability(it.name, o.zone, now))
                    price[ti, zi, ci] = p
                    avail[ti, zi, ci] = live
            # Reserved offerings come from the resolved reservation store,
            # not the type's own offering list: committed price (0 for a
            # plain ODCR — already paid) while slots remain, ICE mask on top.
            if use_market:
                # window encoding: honors [start_s, end_s) bounds and slot
                # exhaustion; a plain open-ended reservation encodes exactly
                # like the legacy branch below
                apply_window_columns(
                    price, avail, names, self.zones,
                    windows_from_reservations(self.reservations.list()),
                    now, unavailable=self.unavailable,
                )
            else:
                # KARPENTER_TPU_MARKET=0: the pre-market encoding, kept
                # verbatim for byte-identity (tests/test_market.py)
                reserved_remaining: dict[tuple[str, str], int] = {}
                for r in self.reservations.list():
                    k = (r.instance_type, r.zone)
                    reserved_remaining[k] = reserved_remaining.get(k, 0) + r.remaining
                for ti, it in enumerate(self._types):
                    for zi, zone in enumerate(self.zones):
                        if reserved_remaining.get((it.name, zone), 0) > 0:
                            ci = lbl.RESERVED_INDEX
                            price[ti, zi, ci] = 0.0
                            avail[ti, zi, ci] = not self.unavailable.is_unavailable(
                                it.name, zone, lbl.CAPACITY_TYPE_RESERVED
                            )
            return CatalogTensors(
                names=names,
                zones=self.zones,
                capacity=C,
                price=price,
                available=avail,
                key=self.cache_key(),
            )


@dataclass(frozen=True)
class CatalogTensors:
    """The device-facing catalog snapshot. ``capacity[T, R]`` is allocatable
    (overhead already subtracted); ``price``/``available`` are
    [T, Z, NUM_CAPACITY_TYPES] with capacity-type axis (0=on-demand, 1=spot,
    2=reserved) and ICE already masked."""

    names: tuple[str, ...]
    zones: tuple[str, ...]
    capacity: np.ndarray
    price: np.ndarray
    available: np.ndarray
    key: tuple = field(default=())

    def min_price(self) -> np.ndarray:
        """[T] cheapest available offering price per type (inf if none)."""
        masked = np.where(self.available, self.price, np.inf)
        return masked.min(axis=(1, 2))

    def any_available(self) -> np.ndarray:
        return self.available.any(axis=(1, 2))
