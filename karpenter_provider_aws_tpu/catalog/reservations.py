"""Capacity reservations: pre-paid, count-limited capacity pools.

The reservation-aware analogue of on-demand capacity reservations (ODCR):
a reservation pins (instance_type, zone) capacity the cluster has already
paid for, so the solver should prefer it over spot/on-demand whenever
compatible — modeled as a third capacity type ``reserved`` whose offering
price is 0 (marginal cost of using what is already bought).

The store is the catalog-side resolved snapshot (populated from the cloud
by the nodeclass status controller, like subnets/security groups); the
cloud keeps ground truth and rejects launches past a reservation's count
with an ICE-classified error, which flows through the standard
unavailable-offerings feedback loop (BASELINE config #5).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Optional


@dataclass
class Reservation:
    id: str
    instance_type: str
    zone: str
    count: int
    used: int = 0       # instances currently drawing from the reservation
    # Market-window fields (market/offerings.py lifts these into
    # OfferingWindow s): a plain ODCR reservation leaves all three at the
    # defaults (open-ended, marginal price 0); a capacity block carries a
    # [start_s, end_s) purchase window and a committed $/hr.
    start_s: Optional[float] = None
    end_s: Optional[float] = None
    committed_price: float = 0.0

    @property
    def remaining(self) -> int:
        return max(self.count - self.used, 0)

    def open_at(self, now: Optional[float]) -> bool:
        """Inside the purchase window (``now=None`` = ignore the clock,
        the pre-market call shape)."""
        if now is None:
            return True
        if self.start_s is not None and now < self.start_s:
            return False
        if self.end_s is not None and now >= self.end_s:
            return False
        return True


class ReservationStore:
    """Thread-safe resolved-reservation snapshot with in-flight accounting."""

    def __init__(self):
        self._lock = threading.RLock()
        self._by_id: dict[str, Reservation] = {}
        self._seq = 0

    def update(self, reservations) -> None:
        """Swap in the resolved set (status-controller refresh path)."""
        with self._lock:
            self._by_id = {r.id: r for r in reservations}
            self._seq += 1

    def list(self) -> list[Reservation]:
        with self._lock:
            return list(self._by_id.values())

    def get(self, rid: str) -> Optional[Reservation]:
        with self._lock:
            return self._by_id.get(rid)

    def remaining(self, instance_type: str, zone: str,
                  now: Optional[float] = None) -> int:
        """Slots purchasable for (type, zone). ``now`` excludes windows
        that are not currently open — the market-aware callers (launch
        eligibility, consolidation slot accounting) pass the clock so an
        expired capacity block stops advertising capacity."""
        with self._lock:
            return sum(
                r.remaining
                for r in self._by_id.values()
                if r.instance_type == instance_type and r.zone == zone
                and r.open_at(now)
            )

    def consume(self, instance_type: str, zone: str,
                now: Optional[float] = None) -> Optional[str]:
        """In-flight decrement at launch commit; returns the reservation id
        or None when exhausted (the launch must fall back / ICE). A closed
        window never serves a slot."""
        with self._lock:
            for r in self._by_id.values():
                if r.instance_type == instance_type and r.zone == zone \
                        and r.remaining > 0 and r.open_at(now):
                    r.used += 1
                    self._seq += 1
                    return r.id
            return None

    def consume_id(self, rid: str) -> bool:
        """In-flight decrement of the specific reservation the cloud drew
        (the launch result's reservation id). Falls back to False when the
        store hasn't discovered that id yet — the next status reconcile
        syncs the true count."""
        with self._lock:
            r = self._by_id.get(rid)
            if r is not None and r.remaining > 0:
                r.used += 1
                self._seq += 1
                return True
            return False

    def release(self, rid: str) -> None:
        """Instance backed by the reservation terminated; capacity returns."""
        with self._lock:
            r = self._by_id.get(rid)
            if r is not None and r.used > 0:
                r.used -= 1
                self._seq += 1

    def seq_num(self) -> int:
        with self._lock:
            return self._seq

    def flush(self) -> None:
        with self._lock:
            self._by_id.clear()
            self._seq += 1
