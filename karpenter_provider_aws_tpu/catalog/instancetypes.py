"""Instance types: the capacity catalog and its generator.

The reference ships ~700 EC2 types discovered via DescribeInstanceTypes and
two 12k-line generated tables (``zz_generated.vpclimits.go``,
``zz_generated.bandwidth.go``). Here the catalog is produced by a
deterministic generator spanning the same axes — categories x generations x
sizes x cpu-architectures, plus GPU/accelerator/storage families — so tests
and benches run hermetically at reference scale without any cloud API.

Capacity/overhead math parity: ``pkg/providers/instancetype/types.go``
 - ENI-limited pod count        types.go:326-340
 - VM-overhead-adjusted memory  types.go:205-215
 - kube-reserved CPU curve      types.go:364-383
 - kube-reserved memory + eviction thresholds  types.go:389-416
"""

from __future__ import annotations

import hashlib
import math
from dataclasses import dataclass, field
from typing import Optional

from ..models import labels as lbl
from ..models.requirements import Requirements
from ..models.resources import ResourceVector

DEFAULT_REGION = "region-1"
DEFAULT_ZONES = ("zone-a", "zone-b", "zone-c", "zone-d")

# Local-zone modeling (parity: the localzone e2e suite). Zones named
# "<region>-lz<N>" — or listed here explicitly — carry a narrow stocked
# family set, on-demand only, at a price premium, like real local zones.
LOCAL_ZONE_NAMES: set = set()
LOCAL_ZONE_FAMILIES = ("c5", "m5", "r5", "g4dn")
LOCAL_ZONE_PRICE_FACTOR = 1.2


@dataclass(frozen=True)
class Offering:
    """One purchasable (zone, capacity-type) slice of an instance type
    (parity: cloudprovider.Offerings built at instancetype.go:252-293).

    Reserved offerings built from a reservation window additionally carry
    ``remaining`` slot count and ``expires_at`` (window end); a price sort
    must use :meth:`usable`, not ``available`` — a committed-price (often
    $0) window with no remaining slots or past its end is not purchasable
    no matter what its price says."""

    zone: str
    capacity_type: str
    price: float
    available: bool
    remaining: Optional[int] = None   # None = not slot-counted
    expires_at: Optional[float] = None  # None = open-ended

    def usable(self, now: Optional[float] = None) -> bool:
        """Purchasable right now: available, slots remain (when counted),
        and the window has not expired (when bounded)."""
        if not self.available:
            return False
        if self.remaining is not None and self.remaining <= 0:
            return False
        if self.expires_at is not None and now is not None and now >= self.expires_at:
            return False
        return True


@dataclass
class InstanceType:
    name: str
    category: str           # c | m | r | t | x | i | g | p | inf | trn
    family: str             # e.g. "c7g"
    generation: int
    size: str               # "xlarge" ...
    arch: str               # amd64 | arm64
    os: str = "linux"
    vcpus: int = 2
    memory_mib: int = 4096
    network_bandwidth_mbps: int = 1000
    ebs_bandwidth_mbps: int = 1000
    max_enis: int = 3
    ips_per_eni: int = 10
    branch_enis: int = 0    # pod-ENI branch interfaces (security-group-per-pod)
    local_nvme_gib: int = 0
    gpu_manufacturer: str = ""
    gpu_name: str = ""
    gpu_count: int = 0
    gpu_memory_mib: int = 0
    accelerator_manufacturer: str = ""
    accelerator_name: str = ""
    accelerator_count: int = 0
    efa_count: int = 0
    bare_metal: bool = False
    hypervisor: str = "nitro"
    encryption_in_transit: bool = True
    region: str = DEFAULT_REGION
    offerings: list[Offering] = field(default_factory=list)

    # -- derived -----------------------------------------------------------
    def eni_limited_pods(self) -> int:
        """parity: types.go:326-340 — enis * (ips-per-eni - 1) + 2."""
        return self.max_enis * (self.ips_per_eni - 1) + 2

    def capacity(self, max_pods: Optional[int] = None, ephemeral_gib: int = 20,
                 instance_store_policy: Optional[str] = None) -> ResourceVector:
        # Memoized per (max_pods, ephemeral_gib, policy): the limits/launch
        # loops call this once per PLAN NODE and the quantity re-parse
        # dominated their host time at thousands of nodes. A fresh copy is
        # returned so a caller mutating its vector cannot poison the memo.
        key = (max_pods, ephemeral_gib, instance_store_policy)
        memo = self.__dict__.get("_capacity_memo")
        if memo is None:
            memo = {}
            self.__dict__["_capacity_memo"] = memo
        v = memo.get(key)
        if v is None:
            pods = max_pods if max_pods is not None else self.eni_limited_pods()
            # Instance-store disks become ephemeral-storage ONLY under the
            # RAID0 policy; otherwise the EBS root volume's size is the
            # node's ephemeral capacity (parity: types.go:218-224
            # ephemeralStorage — RAID0 -> InstanceStorageInfo.TotalSizeInGB,
            # else block-device size).
            if instance_store_policy == "RAID0" and self.local_nvme_gib:
                ephemeral = self.local_nvme_gib
            else:
                ephemeral = ephemeral_gib
            v = ResourceVector.from_map(
                {
                    "cpu": self.vcpus,
                    "memory": f"{self.memory_mib}Mi",
                    "pods": pods,
                    "ephemeral-storage": f"{ephemeral}Gi",
                    "nvidia.com/gpu": self.gpu_count if self.gpu_manufacturer == "nvidia" else 0,
                    "amd.com/gpu": self.gpu_count if self.gpu_manufacturer == "amd" else 0,
                    "aws.amazon.com/neuron": self.accelerator_count if self.accelerator_manufacturer == "aws" else 0,
                    "habana.ai/gaudi": self.accelerator_count if self.accelerator_manufacturer == "habana" else 0,
                    "vpc.amazonaws.com/efa": self.efa_count,
                    "vpc.amazonaws.com/pod-eni": self.branch_enis,
                }
            ).v
            memo[key] = v
        return ResourceVector(v.copy())

    def labels(self) -> dict[str, str]:
        """The node labels this type advertises (parity: types.go:75-161
        computeRequirements — 20+ requirement labels incl. GPU/accelerator)."""
        out = {
            lbl.INSTANCE_TYPE_LABEL: self.name,
            lbl.ARCH: self.arch,
            lbl.OS: self.os,
            lbl.TOPOLOGY_REGION: self.region,
            lbl.INSTANCE_CATEGORY: self.category,
            lbl.INSTANCE_FAMILY: self.family,
            lbl.INSTANCE_GENERATION: str(self.generation),
            lbl.INSTANCE_SIZE: self.size,
            lbl.INSTANCE_CPU: str(self.vcpus),
            lbl.INSTANCE_CPU_MANUFACTURER: "arm-designer" if self.arch == "arm64" else "x86-vendor",
            lbl.INSTANCE_MEMORY: str(self.memory_mib),
            lbl.INSTANCE_HYPERVISOR: "" if self.bare_metal else self.hypervisor,
            lbl.INSTANCE_ENCRYPTION_IN_TRANSIT: str(self.encryption_in_transit).lower(),
            lbl.INSTANCE_NETWORK_BANDWIDTH: str(self.network_bandwidth_mbps),
            lbl.INSTANCE_EBS_BANDWIDTH: str(self.ebs_bandwidth_mbps),
            lbl.INSTANCE_LOCAL_NVME: str(self.local_nvme_gib),
        }
        if self.gpu_count:
            out[lbl.INSTANCE_GPU_MANUFACTURER] = self.gpu_manufacturer
            out[lbl.INSTANCE_GPU_NAME] = self.gpu_name
            out[lbl.INSTANCE_GPU_COUNT] = str(self.gpu_count)
            out[lbl.INSTANCE_GPU_MEMORY] = str(self.gpu_memory_mib)
        if self.accelerator_count:
            out[lbl.INSTANCE_ACCELERATOR_MANUFACTURER] = self.accelerator_manufacturer
            out[lbl.INSTANCE_ACCELERATOR_NAME] = self.accelerator_name
            out[lbl.INSTANCE_ACCELERATOR_COUNT] = str(self.accelerator_count)
        return out

    def requirements(self) -> Requirements:
        reqs = Requirements.from_labels(self.labels())
        zones = sorted({o.zone for o in self.offerings if o.available})
        captypes = sorted({o.capacity_type for o in self.offerings if o.available})
        if zones:
            from ..models.requirements import Operator, Requirement
            reqs.add(Requirement(lbl.TOPOLOGY_ZONE, Operator.IN, tuple(zones)))
            reqs.add(Requirement(lbl.CAPACITY_TYPE, Operator.IN, tuple(captypes)))
        return reqs

    def cheapest_price(self, capacity_types=lbl.CAPACITY_TYPES, zones=None,
                       now: Optional[float] = None) -> float:
        # usable(), not available: an expired or slot-exhausted reservation
        # window carries a committed price (often $0) that would otherwise
        # win every price sort while selling capacity that does not exist
        # (ISSUE 16 regression: tests/test_market.py)
        prices = [
            o.price
            for o in self.offerings
            if o.usable(now) and o.capacity_type in capacity_types and (zones is None or o.zone in zones)
        ]
        return min(prices) if prices else math.inf


# ---------------------------------------------------------------------------
# Deterministic catalog generator (replaces the reference's generated tables).
# ---------------------------------------------------------------------------

_MEM_PER_VCPU_GIB = {"c": 2, "m": 4, "r": 8, "x": 16, "i": 8, "t": 4, "d": 6}

# Size -> vCPUs on the standard nitro ladder (large = 2 doubling upward).
_SIZE_VCPUS = {
    "nano": 2, "micro": 2, "small": 2, "medium": 2, "large": 2, "xlarge": 4,
    "2xlarge": 8, "3xlarge": 12, "4xlarge": 16, "6xlarge": 24, "8xlarge": 32,
    "9xlarge": 36, "10xlarge": 40, "12xlarge": 48, "16xlarge": 64,
    "18xlarge": 72, "24xlarge": 96, "32xlarge": 128, "48xlarge": 192,
    "56xlarge": 224, "112xlarge": 448,
    "metal-16xl": 64, "metal-24xl": 96, "metal-32xl": 128, "metal-48xl": 192,
}
# Known ladder exceptions (legacy xen-era shapes).
_VCPU_OVERRIDES = {
    "c1.xlarge": 8, "m2.xlarge": 2, "m2.2xlarge": 4, "m2.4xlarge": 8,
    "cr1.8xlarge": 32, "t1.micro": 1, "t2.nano": 1, "t2.micro": 1,
    "t2.small": 1, "m1.small": 1, "m1.medium": 1, "m3.medium": 1,
}
# Memory GiB per vCPU by category prefix; per-family overrides below.
_MEM_PER_VCPU_BY_CATEGORY = {
    "a": 2, "c": 2, "m": 4, "r": 8, "x": 16, "z": 8, "i": 8, "im": 4,
    "is": 6, "d": 7, "h": 8, "f": 15, "t": 4, "g": 4, "gr": 8, "p": 8,
    "inf": 2, "trn": 4, "dl": 8, "vt": 2, "hpc": 2, "u": 16, "cr": 8,
}
_MEM_PER_VCPU_BY_FAMILY = {"p4d": 12, "p4de": 12, "p5": 10, "inf2": 4, "g5g": 2}

# GPU families: (manufacturer, gpu name, per-GPU memory MiB, count by size).
_GPU_INFO = {
    "g2": ("nvidia", "k520", 4096, {"2xlarge": 1, "8xlarge": 4}),
    "g3": ("nvidia", "m60", 8192, {"4xlarge": 1, "8xlarge": 2, "16xlarge": 4}),
    "g3s": ("nvidia", "m60", 8192, {"xlarge": 1}),
    "g4ad": ("amd", "radeon-pro-v520", 8192,
             {"xlarge": 1, "2xlarge": 1, "4xlarge": 1, "8xlarge": 2, "16xlarge": 4}),
    "g4dn": ("nvidia", "t4", 16384,
             {"xlarge": 1, "2xlarge": 1, "4xlarge": 1, "8xlarge": 1,
              "12xlarge": 4, "16xlarge": 1, "metal": 8}),
    "g5": ("nvidia", "a10g", 24576,
           {"xlarge": 1, "2xlarge": 1, "4xlarge": 1, "8xlarge": 1,
            "12xlarge": 4, "16xlarge": 1, "24xlarge": 4, "48xlarge": 8}),
    "g5g": ("nvidia", "t4g", 16384,
            {"xlarge": 1, "2xlarge": 1, "4xlarge": 1, "8xlarge": 1,
             "16xlarge": 2, "metal": 2}),
    "g6": ("nvidia", "l4", 24576,
           {"xlarge": 1, "2xlarge": 1, "4xlarge": 1, "8xlarge": 1,
            "12xlarge": 4, "16xlarge": 1, "24xlarge": 4, "48xlarge": 8}),
    "gr6": ("nvidia", "l4", 24576, {"4xlarge": 1, "8xlarge": 1}),
    "p2": ("nvidia", "k80", 12288, {"xlarge": 1, "8xlarge": 8, "16xlarge": 16}),
    "p3": ("nvidia", "v100", 16384, {"2xlarge": 1, "8xlarge": 4, "16xlarge": 8}),
    "p3dn": ("nvidia", "v100", 32768, {"24xlarge": 8}),
    "p4d": ("nvidia", "a100", 40960, {"24xlarge": 8}),
    "p4de": ("nvidia", "a100", 81920, {"24xlarge": 8}),
    "p5": ("nvidia", "h100", 81920, {"48xlarge": 8}),
}
# Accelerator families: (manufacturer, name, count by size).
_ACCEL_INFO = {
    "inf1": ("aws", "inferentia", {"xlarge": 1, "2xlarge": 1, "6xlarge": 4, "24xlarge": 16}),
    "inf2": ("aws", "inferentia2", {"xlarge": 1, "8xlarge": 1, "24xlarge": 6, "48xlarge": 12}),
    "trn1": ("aws", "trainium", {"2xlarge": 1, "32xlarge": 16}),
    "trn1n": ("aws", "trainium", {"32xlarge": 16}),
    "dl1": ("habana", "gaudi", {"24xlarge": 8}),
    "vt1": ("xilinx", "u30", {"3xlarge": 1, "6xlarge": 2, "24xlarge": 8}),
    "f1": ("xilinx", "fpga", {"2xlarge": 1, "4xlarge": 2, "16xlarge": 8}),
}
# EFA interface counts for the EFA-bearing flagships.
_EFA_COUNTS = {
    "p4d.24xlarge": 4, "p4de.24xlarge": 4, "p5.48xlarge": 32,
    "trn1.32xlarge": 8, "trn1n.32xlarge": 16, "dl1.24xlarge": 4,
    "hpc7g.4xlarge": 1, "hpc7g.8xlarge": 1, "hpc7g.16xlarge": 1,
}


def _h(name: str) -> int:
    """Stable small hash for deterministic jitter."""
    return int.from_bytes(hashlib.sha256(name.encode()).digest()[:4], "big")


def _eni_limits(vcpus: int) -> tuple[int, int]:
    if vcpus <= 2:
        return 3, 10
    if vcpus <= 8:
        return 4, 15
    if vcpus <= 16:
        return 4, 30
    if vcpus <= 48:
        return 8, 30
    return 15, 50


def _network_mbps(vcpus: int, variant: str) -> int:
    base = min(25_000, 750 * vcpus)
    return base * (4 if variant == "n" else 1)


def _branch_enis(vcpus: int, hypervisor: str) -> int:
    """Pod-ENI branch-interface model: nitro-only, scales with size
    (parity: the trunk/branch columns of zz_generated.vpclimits.go,
    consumed as vpc.amazonaws.com/pod-eni at types.go:255-262)."""
    if hypervisor != "nitro":
        return 0
    return min(107, 6 * vcpus)


def _apply_generated_tables(types: list["InstanceType"], apply_generated: bool = True) -> None:
    """Overlay the committed static tables (the codegen layer's output,
    mirroring how the reference consults its zz_generated.* maps at
    types.go:122-124 and types.go:255-262). Falls back to the in-module
    model when a table is absent or lacks an entry. ``apply_generated=False``
    keeps pure model output — used by the codegen generators themselves so a
    stale table is never snapshotted back into itself."""
    LIMITS: dict = {}
    INSTANCE_TYPE_BANDWIDTH_MBPS: dict = {}
    if apply_generated:
        try:
            from .zz_generated_vpclimits import LIMITS  # type: ignore[no-redef]
        except ImportError:
            pass
        try:
            from .zz_generated_bandwidth import INSTANCE_TYPE_BANDWIDTH_MBPS  # type: ignore[no-redef]
        except ImportError:
            pass
    for it in types:
        lim = LIMITS.get(it.name)
        if lim is not None:
            it.max_enis, it.ips_per_eni, it.branch_enis = lim
        else:
            it.branch_enis = _branch_enis(it.vcpus, it.hypervisor)
        bw = INSTANCE_TYPE_BANDWIDTH_MBPS.get(it.name)
        if bw is not None:
            it.network_bandwidth_mbps = bw


def generate_catalog(zones=DEFAULT_ZONES, apply_generated: bool = True) -> list[InstanceType]:
    """The real us-east-1 catalog (776 types), built from the committed
    ``aws_snapshot.json`` — real membership, real on-demand prices, real
    ENI/branch limits and bandwidth (parsed from the reference's generated
    data tables by ``codegen/aws_snapshot_gen.py``; round-3 VERDICT missing
    #1: no invented instance types). Per-type specs the snapshot does not
    carry (vCPUs, memory, GPU/accelerator shapes) derive from the public
    size ladder and per-family tables below."""
    import json
    import pathlib
    import re as _re

    snap_path = pathlib.Path(__file__).resolve().parent / "aws_snapshot.json"
    snapshot = json.loads(snap_path.read_text())["types"]

    def fam_of(name: str) -> str:
        return name.split(".", 1)[0]

    def size_of(name: str) -> str:
        return name.split(".", 1)[1]

    def is_arm(family: str) -> bool:
        # graviton lines: letters, generation digit(s), then 'g' (c7g,
        # m6gd, x2gd, im4gn, g5g, hpc7g, t4g, i4g, is4gen) — plus a1
        return family == "a1" or bool(_re.match(r"^[a-z]+\d+g", family))

    def vcpus_of(name: str, family: str, size: str, fam_max: dict) -> int:
        ov = _VCPU_OVERRIDES.get(name)
        if ov is not None:
            return ov
        if size == "metal":
            return fam_max.get(family, 96)
        v = _SIZE_VCPUS.get(size, 2)
        if size == "medium" and is_arm(family):
            return 1  # graviton .medium is 1 vCPU
        return v

    # pass 1: per-family max non-metal vCPUs (sizes 'metal' inherit it)
    fam_max: dict[str, int] = {}
    for name in snapshot:
        family, size = fam_of(name), size_of(name)
        if not size.startswith("metal"):
            v = _VCPU_OVERRIDES.get(name, _SIZE_VCPUS.get(size, 2))
            fam_max[family] = max(fam_max.get(family, 2), v)

    out: list[InstanceType] = []
    for name, row in snapshot.items():
        family, size = fam_of(name), size_of(name)
        category = _re.match(r"^[a-z]+", family).group(0)
        digits = _re.findall(r"\d+", family)
        generation = int(digits[-1]) if digits else 1
        arch = "arm64" if is_arm(family) else "amd64"
        vcpus = vcpus_of(name, family, size, fam_max)
        # memory: u-<N>tb1 encodes its RAM in the family name; everything
        # else uses the per-family/category GiB-per-vCPU ratio
        u_m = _re.match(r"^u-(\d+)tb1$", family)
        if u_m:
            mem_gib = int(u_m.group(1)) * 1024
        elif category == "t":
            # burstables: memory tracks the size name, not the vCPU count
            mem_gib = {
                "nano": 0.5, "micro": 1, "small": 2, "medium": 4,
                "large": 8, "xlarge": 16, "2xlarge": 32,
            }.get(size, 8)
        else:
            ratio = _MEM_PER_VCPU_BY_FAMILY.get(
                family, _MEM_PER_VCPU_BY_CATEGORY.get(category, 4)
            )
            mem_gib = vcpus * ratio
        bare_metal = size.startswith("metal")
        hyp = row.get("hyp", "nitro" if generation >= 5 else "xen")
        suffix = family[len(category) + len(digits[-1] if digits else ""):] if digits else ""
        # local NVMe: 'd' variant lines and the storage categories
        has_nvme = ("d" in suffix and family not in ("g4ad",)) or family in (
            "g4ad", "g5", "p5", "z1d"
        ) or category in ("i", "im", "is", "d", "h")
        gpu = _GPU_INFO.get(family)
        accel = _ACCEL_INFO.get(family)
        enis, ips = row.get("enis"), row.get("ips")
        if not enis or not ips:
            enis, ips = _eni_limits(vcpus)
        bw = row.get("bw") or _network_mbps(vcpus, "n" if suffix.endswith("n") else "")
        # EFA: the per-name table for the accelerator flagships, plus the
        # rule the network-variant ('n') and HPC flagships follow — a pod
        # requesting vpc.amazonaws.com/efa must keep finding c5n.18xlarge /
        # c6gn.16xlarge / hpc6a-class candidates
        efa = _EFA_COUNTS.get(name, 0)
        if not efa and (category == "hpc" or ("n" in suffix and vcpus >= 64)):
            efa = 1
        it = InstanceType(
            name=name, category=category, family=family, generation=generation,
            size=size, arch=arch, vcpus=vcpus, memory_mib=int(mem_gib * 1024),
            network_bandwidth_mbps=int(bw),
            ebs_bandwidth_mbps=min(19_000, 600 * vcpus),
            max_enis=int(enis), ips_per_eni=int(ips),
            branch_enis=int(row.get("branch", 0)) if row.get("trunk") else 0,
            local_nvme_gib=(vcpus * 75 if has_nvme else 0),
            efa_count=efa,
            bare_metal=bare_metal,
            hypervisor="" if bare_metal else (hyp or "nitro"),
        )
        if gpu and size in gpu[3]:
            it.gpu_manufacturer, it.gpu_name, it.gpu_memory_mib = gpu[0], gpu[1], gpu[2]
            it.gpu_count = gpu[3][size]
        if accel and size in accel[2]:
            it.accelerator_manufacturer, it.accelerator_name = accel[0], accel[1]
            it.accelerator_count = accel[2][size]
        out.append(it)

    _apply_generated_tables(out, apply_generated=apply_generated)

    # Attach offerings (prices via the pricing model, deterministic
    # availability holes so tests exercise the offering mask).
    from .pricing import PricingProvider

    pricing = PricingProvider()
    for it in out:
        offerings = []
        for zi, zone in enumerate(zones):
            if zone in LOCAL_ZONE_NAMES or zone.split("-lz")[0] != zone:
                # Local zones (parity: the localzone e2e suite): only a
                # narrow family set is stocked, and spot is not offered.
                present = it.family in LOCAL_ZONE_FAMILIES
                od = pricing.on_demand_price(it) * LOCAL_ZONE_PRICE_FACTOR
                offerings.append(Offering(zone, lbl.CAPACITY_TYPE_ON_DEMAND, od, present))
                continue
            # Newest-gen arm and exotic families are missing from some zones.
            present = not (_h(f"{it.family}:{zone}") % 17 == 0 and zi >= 2)
            od = pricing.on_demand_price(it)
            spot = pricing.spot_price(it, zone)
            offerings.append(Offering(zone, lbl.CAPACITY_TYPE_ON_DEMAND, od, present))
            offerings.append(Offering(zone, lbl.CAPACITY_TYPE_SPOT, spot, present))
        it.offerings = offerings
    return out
