"""Instance types: the capacity catalog and its generator.

The reference ships ~700 EC2 types discovered via DescribeInstanceTypes and
two 12k-line generated tables (``zz_generated.vpclimits.go``,
``zz_generated.bandwidth.go``). Here the catalog is produced by a
deterministic generator spanning the same axes — categories x generations x
sizes x cpu-architectures, plus GPU/accelerator/storage families — so tests
and benches run hermetically at reference scale without any cloud API.

Capacity/overhead math parity: ``pkg/providers/instancetype/types.go``
 - ENI-limited pod count        types.go:326-340
 - VM-overhead-adjusted memory  types.go:205-215
 - kube-reserved CPU curve      types.go:364-383
 - kube-reserved memory + eviction thresholds  types.go:389-416
"""

from __future__ import annotations

import hashlib
import math
from dataclasses import dataclass, field
from typing import Optional

from ..models import labels as lbl
from ..models.requirements import Requirements
from ..models.resources import ResourceVector

DEFAULT_REGION = "region-1"
DEFAULT_ZONES = ("zone-a", "zone-b", "zone-c", "zone-d")

# Local-zone modeling (parity: the localzone e2e suite). Zones named
# "<region>-lz<N>" — or listed here explicitly — carry a narrow stocked
# family set, on-demand only, at a price premium, like real local zones.
LOCAL_ZONE_NAMES: set = set()
LOCAL_ZONE_FAMILIES = ("c5", "m5", "r5", "g4dn")
LOCAL_ZONE_PRICE_FACTOR = 1.2


@dataclass(frozen=True)
class Offering:
    """One purchasable (zone, capacity-type) slice of an instance type
    (parity: cloudprovider.Offerings built at instancetype.go:252-293)."""

    zone: str
    capacity_type: str
    price: float
    available: bool


@dataclass
class InstanceType:
    name: str
    category: str           # c | m | r | t | x | i | g | p | inf | trn
    family: str             # e.g. "c7g"
    generation: int
    size: str               # "xlarge" ...
    arch: str               # amd64 | arm64
    os: str = "linux"
    vcpus: int = 2
    memory_mib: int = 4096
    network_bandwidth_mbps: int = 1000
    ebs_bandwidth_mbps: int = 1000
    max_enis: int = 3
    ips_per_eni: int = 10
    branch_enis: int = 0    # pod-ENI branch interfaces (security-group-per-pod)
    local_nvme_gib: int = 0
    gpu_manufacturer: str = ""
    gpu_name: str = ""
    gpu_count: int = 0
    gpu_memory_mib: int = 0
    accelerator_manufacturer: str = ""
    accelerator_name: str = ""
    accelerator_count: int = 0
    efa_count: int = 0
    bare_metal: bool = False
    hypervisor: str = "nitro"
    encryption_in_transit: bool = True
    region: str = DEFAULT_REGION
    offerings: list[Offering] = field(default_factory=list)

    # -- derived -----------------------------------------------------------
    def eni_limited_pods(self) -> int:
        """parity: types.go:326-340 — enis * (ips-per-eni - 1) + 2."""
        return self.max_enis * (self.ips_per_eni - 1) + 2

    def capacity(self, max_pods: Optional[int] = None, ephemeral_gib: int = 20) -> ResourceVector:
        # Memoized per (max_pods, ephemeral_gib): the limits/launch loops call
        # this once per PLAN NODE and the quantity re-parse dominated their
        # host time at thousands of nodes. A fresh copy is returned so a
        # caller mutating its vector cannot poison the memo.
        key = (max_pods, ephemeral_gib)
        memo = self.__dict__.get("_capacity_memo")
        if memo is None:
            memo = {}
            self.__dict__["_capacity_memo"] = memo
        v = memo.get(key)
        if v is None:
            pods = max_pods if max_pods is not None else self.eni_limited_pods()
            v = ResourceVector.from_map(
                {
                    "cpu": self.vcpus,
                    "memory": f"{self.memory_mib}Mi",
                    "pods": pods,
                    "ephemeral-storage": f"{max(self.local_nvme_gib, ephemeral_gib)}Gi",
                    "nvidia.com/gpu": self.gpu_count if self.gpu_manufacturer == "nvidia" else 0,
                    "amd.com/gpu": self.gpu_count if self.gpu_manufacturer == "amd" else 0,
                    "aws.amazon.com/neuron": self.accelerator_count if self.accelerator_manufacturer == "aws" else 0,
                    "vpc.amazonaws.com/efa": self.efa_count,
                    "vpc.amazonaws.com/pod-eni": self.branch_enis,
                }
            ).v
            memo[key] = v
        return ResourceVector(v.copy())

    def labels(self) -> dict[str, str]:
        """The node labels this type advertises (parity: types.go:75-161
        computeRequirements — 20+ requirement labels incl. GPU/accelerator)."""
        out = {
            lbl.INSTANCE_TYPE_LABEL: self.name,
            lbl.ARCH: self.arch,
            lbl.OS: self.os,
            lbl.TOPOLOGY_REGION: self.region,
            lbl.INSTANCE_CATEGORY: self.category,
            lbl.INSTANCE_FAMILY: self.family,
            lbl.INSTANCE_GENERATION: str(self.generation),
            lbl.INSTANCE_SIZE: self.size,
            lbl.INSTANCE_CPU: str(self.vcpus),
            lbl.INSTANCE_CPU_MANUFACTURER: "arm-designer" if self.arch == "arm64" else "x86-vendor",
            lbl.INSTANCE_MEMORY: str(self.memory_mib),
            lbl.INSTANCE_HYPERVISOR: "" if self.bare_metal else self.hypervisor,
            lbl.INSTANCE_ENCRYPTION_IN_TRANSIT: str(self.encryption_in_transit).lower(),
            lbl.INSTANCE_NETWORK_BANDWIDTH: str(self.network_bandwidth_mbps),
            lbl.INSTANCE_EBS_BANDWIDTH: str(self.ebs_bandwidth_mbps),
            lbl.INSTANCE_LOCAL_NVME: str(self.local_nvme_gib),
        }
        if self.gpu_count:
            out[lbl.INSTANCE_GPU_MANUFACTURER] = self.gpu_manufacturer
            out[lbl.INSTANCE_GPU_NAME] = self.gpu_name
            out[lbl.INSTANCE_GPU_COUNT] = str(self.gpu_count)
            out[lbl.INSTANCE_GPU_MEMORY] = str(self.gpu_memory_mib)
        if self.accelerator_count:
            out[lbl.INSTANCE_ACCELERATOR_MANUFACTURER] = self.accelerator_manufacturer
            out[lbl.INSTANCE_ACCELERATOR_NAME] = self.accelerator_name
            out[lbl.INSTANCE_ACCELERATOR_COUNT] = str(self.accelerator_count)
        return out

    def requirements(self) -> Requirements:
        reqs = Requirements.from_labels(self.labels())
        zones = sorted({o.zone for o in self.offerings if o.available})
        captypes = sorted({o.capacity_type for o in self.offerings if o.available})
        if zones:
            from ..models.requirements import Operator, Requirement
            reqs.add(Requirement(lbl.TOPOLOGY_ZONE, Operator.IN, tuple(zones)))
            reqs.add(Requirement(lbl.CAPACITY_TYPE, Operator.IN, tuple(captypes)))
        return reqs

    def cheapest_price(self, capacity_types=lbl.CAPACITY_TYPES, zones=None) -> float:
        prices = [
            o.price
            for o in self.offerings
            if o.available and o.capacity_type in capacity_types and (zones is None or o.zone in zones)
        ]
        return min(prices) if prices else math.inf


# ---------------------------------------------------------------------------
# Deterministic catalog generator (replaces the reference's generated tables).
# ---------------------------------------------------------------------------

_SIZES = (
    # (size, vcpus multiplier over .large=2)
    ("large", 1), ("xlarge", 2), ("2xlarge", 4), ("3xlarge", 6), ("4xlarge", 8),
    ("6xlarge", 12), ("8xlarge", 16), ("12xlarge", 24), ("16xlarge", 32),
    ("24xlarge", 48),
)
_MEM_PER_VCPU_GIB = {"c": 2, "m": 4, "r": 8, "x": 16, "i": 8, "t": 4, "d": 6}


def _h(name: str) -> int:
    """Stable small hash for deterministic jitter."""
    return int.from_bytes(hashlib.sha256(name.encode()).digest()[:4], "big")


def _eni_limits(vcpus: int) -> tuple[int, int]:
    if vcpus <= 2:
        return 3, 10
    if vcpus <= 8:
        return 4, 15
    if vcpus <= 16:
        return 4, 30
    if vcpus <= 48:
        return 8, 30
    return 15, 50


def _network_mbps(vcpus: int, variant: str) -> int:
    base = min(25_000, 750 * vcpus)
    return base * (4 if variant == "n" else 1)


def _branch_enis(vcpus: int, hypervisor: str) -> int:
    """Pod-ENI branch-interface model: nitro-only, scales with size
    (parity: the trunk/branch columns of zz_generated.vpclimits.go,
    consumed as vpc.amazonaws.com/pod-eni at types.go:255-262)."""
    if hypervisor != "nitro":
        return 0
    return min(107, 6 * vcpus)


def _apply_generated_tables(types: list["InstanceType"], apply_generated: bool = True) -> None:
    """Overlay the committed static tables (the codegen layer's output,
    mirroring how the reference consults its zz_generated.* maps at
    types.go:122-124 and types.go:255-262). Falls back to the in-module
    model when a table is absent or lacks an entry. ``apply_generated=False``
    keeps pure model output — used by the codegen generators themselves so a
    stale table is never snapshotted back into itself."""
    LIMITS: dict = {}
    INSTANCE_TYPE_BANDWIDTH_MBPS: dict = {}
    if apply_generated:
        try:
            from .zz_generated_vpclimits import LIMITS  # type: ignore[no-redef]
        except ImportError:
            pass
        try:
            from .zz_generated_bandwidth import INSTANCE_TYPE_BANDWIDTH_MBPS  # type: ignore[no-redef]
        except ImportError:
            pass
    for it in types:
        lim = LIMITS.get(it.name)
        if lim is not None:
            it.max_enis, it.ips_per_eni, it.branch_enis = lim
        else:
            it.branch_enis = _branch_enis(it.vcpus, it.hypervisor)
        bw = INSTANCE_TYPE_BANDWIDTH_MBPS.get(it.name)
        if bw is not None:
            it.network_bandwidth_mbps = bw


def generate_catalog(zones=DEFAULT_ZONES, apply_generated: bool = True) -> list[InstanceType]:
    """~700 instance types spanning the reference catalog's axes."""
    out: list[InstanceType] = []

    # General-purpose / compute / memory families x generations x variants.
    for cat in ("c", "m", "r", "x"):
        for gen in (5, 6, 7):
            arch_variants = [("", "amd64")]
            if gen >= 6:
                arch_variants.append(("g", "arm64"))  # graviton-style arm line
            for arch_suffix, arch in arch_variants:
                variants = ["", "d"]  # base, local-nvme
                if cat in ("c", "m", "r"):
                    if arch == "amd64":
                        variants.append("a")  # alt-cpu-vendor line
                        variants.append("n")  # network-optimized
                    elif gen >= 7:
                        variants.append("n")  # arm network line (c7gn-style)
                for variant in variants:
                    family = f"{cat}{gen}{arch_suffix}{variant}"
                    for size, mult in _SIZES:
                        vcpus = 2 * mult
                        mem = int(vcpus * _MEM_PER_VCPU_GIB[cat] * 1024)
                        enis, ips = _eni_limits(vcpus)
                        out.append(
                            InstanceType(
                                name=f"{family}.{size}", category=cat, family=family,
                                generation=gen, size=size, arch=arch, vcpus=vcpus,
                                memory_mib=mem,
                                network_bandwidth_mbps=_network_mbps(vcpus, variant),
                                ebs_bandwidth_mbps=min(19_000, 600 * vcpus),
                                max_enis=enis, ips_per_eni=ips,
                                local_nvme_gib=(vcpus * 75 if variant == "d" else 0),
                                efa_count=(1 if variant == "n" and vcpus >= 32 else 0),
                            )
                        )
                    # bare-metal top end per family (base variant only)
                    if variant == "":
                        vcpus = 96
                        out.append(
                            InstanceType(
                                name=f"{family}.metal", category=cat, family=family,
                                generation=gen, size="metal", arch=arch, vcpus=vcpus,
                                memory_mib=int(vcpus * _MEM_PER_VCPU_GIB[cat] * 1024),
                                network_bandwidth_mbps=25_000, ebs_bandwidth_mbps=19_000,
                                max_enis=15, ips_per_eni=50, bare_metal=True, hypervisor="",
                            )
                        )

    # Burstable families (small sizes).
    for fam, arch in (("t3", "amd64"), ("t3a", "amd64"), ("t4g", "arm64")):
        for size, vcpus, mem_gib in (("micro", 2, 1), ("small", 2, 2), ("medium", 2, 4), ("large", 2, 8), ("xlarge", 4, 16)):
            out.append(
                InstanceType(
                    name=f"{fam}.{size}", category="t", family=fam,
                    generation=int(fam[1]), size=size,
                    arch=arch, vcpus=vcpus, memory_mib=mem_gib * 1024,
                    network_bandwidth_mbps=5_000, ebs_bandwidth_mbps=2_000,
                    max_enis=3, ips_per_eni=6 if vcpus <= 2 else 12,
                )
            )

    # Storage-optimized.
    for gen, sizes in (("i3", _SIZES[:8]), ("i4i", _SIZES[:8]), ("d3", _SIZES[:5])):
        for size, mult in sizes:
            vcpus = 2 * mult
            out.append(
                InstanceType(
                    name=f"{gen}.{size}", category="i", family=gen,
                    generation=int(gen[1]), size=size, arch="amd64", vcpus=vcpus,
                    memory_mib=int(vcpus * 8 * 1024),
                    network_bandwidth_mbps=_network_mbps(vcpus, ""),
                    ebs_bandwidth_mbps=min(19_000, 600 * vcpus),
                    max_enis=_eni_limits(vcpus)[0], ips_per_eni=_eni_limits(vcpus)[1],
                    local_nvme_gib=vcpus * 475,
                )
            )

    # HPC families (EFA-heavy, on-demand-only in practice; modeled as normal).
    for fam, arch, vcpus in (("hpc6a", "amd64", 96), ("hpc7g", "arm64", 64)):
        out.append(
            InstanceType(
                name=f"{fam}.{vcpus}xlarge", category="hpc", family=fam,
                generation=int(fam[3]), size=f"{vcpus}xlarge", arch=arch,
                vcpus=vcpus, memory_mib=vcpus * 4 * 1024,
                network_bandwidth_mbps=100_000, ebs_bandwidth_mbps=2_000,
                max_enis=15, ips_per_eni=50, efa_count=1,
            )
        )

    # GPU families (nvidia).
    for family, gpu_name, gpu_mem, per_gpu_vcpu, sizes in (
        ("g4dn", "t4", 16_384, 2, ((1, "xlarge"), (1, "2xlarge"), (1, "4xlarge"), (4, "12xlarge"), (8, "metal"))),
        ("g5", "a10g", 24_576, 4, ((1, "xlarge"), (1, "2xlarge"), (1, "4xlarge"), (4, "12xlarge"), (8, "48xlarge"))),
        ("g6", "l4", 24_576, 4, ((1, "xlarge"), (1, "2xlarge"), (1, "4xlarge"), (4, "12xlarge"), (8, "48xlarge"))),
        ("p4d", "a100", 40_960, 12, ((8, "24xlarge"),)),
        ("p5", "h100", 81_920, 24, ((8, "48xlarge"),)),
    ):
        for gpus, size in sizes:
            vcpus = max(4, gpus * per_gpu_vcpu * 2)
            out.append(
                InstanceType(
                    name=f"{family}.{size}", category="g" if family.startswith("g") else "p",
                    family=family, generation=int("".join(c for c in family if c.isdigit())),
                    size=size, arch="amd64", vcpus=vcpus,
                    memory_mib=vcpus * 4 * 1024,
                    network_bandwidth_mbps=100_000 if family.startswith("p") else 25_000,
                    ebs_bandwidth_mbps=19_000,
                    max_enis=8, ips_per_eni=30,
                    gpu_manufacturer="nvidia", gpu_name=gpu_name, gpu_count=gpus,
                    gpu_memory_mib=gpu_mem,
                    efa_count=(4 if family == "p5" else (1 if family == "p4d" else 0)),
                    bare_metal=(size == "metal"),
                )
            )

    # Arm GPU line.
    for gpus, size in ((1, "xlarge"), (1, "2xlarge"), (1, "4xlarge"), (1, "8xlarge"), (2, "16xlarge")):
        vcpus = {"xlarge": 4, "2xlarge": 8, "4xlarge": 16, "8xlarge": 32, "16xlarge": 64}[size]
        out.append(
            InstanceType(
                name=f"g5g.{size}", category="g", family="g5g", generation=5,
                size=size, arch="arm64", vcpus=vcpus, memory_mib=vcpus * 2 * 1024,
                network_bandwidth_mbps=25_000, ebs_bandwidth_mbps=9_500,
                max_enis=8, ips_per_eni=30,
                gpu_manufacturer="nvidia", gpu_name="t4g", gpu_count=gpus,
                gpu_memory_mib=16_384,
            )
        )

    # Neuron accelerator families.
    for family, accel, sizes in (
        ("inf1", "inferentia", ((1, "xlarge"), (1, "2xlarge"), (4, "6xlarge"), (16, "24xlarge"))),
        ("inf2", "inferentia2", ((1, "xlarge"), (1, "8xlarge"), (6, "24xlarge"), (12, "48xlarge"))),
        ("trn1", "trainium", ((1, "2xlarge"), (16, "32xlarge"))),
    ):
        for count, size in sizes:
            vcpus = {"xlarge": 4, "2xlarge": 8, "6xlarge": 24, "8xlarge": 32, "24xlarge": 96, "32xlarge": 128, "48xlarge": 192}[size]
            out.append(
                InstanceType(
                    name=f"{family}.{size}", category=family[:3], family=family,
                    generation=int(family[-1]), size=size, arch="amd64", vcpus=vcpus,
                    memory_mib=vcpus * 4 * 1024,
                    network_bandwidth_mbps=100_000 if family == "trn1" else 25_000,
                    ebs_bandwidth_mbps=19_000, max_enis=8, ips_per_eni=30,
                    accelerator_manufacturer="aws", accelerator_name=accel,
                    accelerator_count=count,
                    efa_count=(8 if family == "trn1" and size == "32xlarge" else 0),
                )
            )

    _apply_generated_tables(out, apply_generated=apply_generated)

    # Attach offerings (prices via the pricing model, deterministic
    # availability holes so tests exercise the offering mask).
    from .pricing import PricingProvider

    pricing = PricingProvider()
    for it in out:
        offerings = []
        for zi, zone in enumerate(zones):
            if zone in LOCAL_ZONE_NAMES or zone.split("-lz")[0] != zone:
                # Local zones (parity: the localzone e2e suite): only a
                # narrow family set is stocked, and spot is not offered.
                present = it.family in LOCAL_ZONE_FAMILIES
                od = pricing.on_demand_price(it) * LOCAL_ZONE_PRICE_FACTOR
                offerings.append(Offering(zone, lbl.CAPACITY_TYPE_ON_DEMAND, od, present))
                continue
            # Newest-gen arm and exotic families are missing from some zones.
            present = not (_h(f"{it.family}:{zone}") % 17 == 0 and zi >= 2)
            od = pricing.on_demand_price(it)
            spot = pricing.spot_price(it, zone)
            offerings.append(Offering(zone, lbl.CAPACITY_TYPE_ON_DEMAND, od, present))
            offerings.append(Offering(zone, lbl.CAPACITY_TYPE_SPOT, spot, present))
        it.offerings = offerings
    return out
