"""Pricing: $/hr catalog with static seed prices + live-refresh interface.

Reference parity: ``pkg/providers/pricing/pricing.go`` — compiled-in seed
prices (pricing.go:43), on-demand refresh via a pricing API, per-zone spot
prices with an on-demand-derived default (pricing.go:75-90,141-156), and an
isolated-VPC mode that skips live refresh (pricing.go:164-170).

The price *model* is deterministic (a function of the type's shape), standing
in for the reference's generated ``zz_generated.pricing_*.go`` tables. A
``PriceUpdate`` hook lets a live backend override any entry, mirroring
UpdateOnDemandPricing / UpdateSpotPricing.
"""

from __future__ import annotations

import hashlib
import math
import threading
from typing import TYPE_CHECKING, Mapping, Optional

from ..models import labels as lbl

if TYPE_CHECKING:
    from .instancetypes import InstanceType

#: live-refresh staleness TTL: the reference's pricing controller refreshes
#: hourly; past this age a once-live source is considered stale and
#: observe_staleness publishes a PricingStale Warning (docs/observability.md)
PRICING_STALE_TTL_S = 3900.0

# Seed $/vcpu-hr by category; generation discount compounds 8%/gen newer than 5.
_BASE_VCPU_RATE = {
    "c": 0.0425, "m": 0.0480, "r": 0.0630, "x": 0.0835,
    "i": 0.0780, "t": 0.0209, "d": 0.0690,
    "g": 0.2500, "p": 0.7500, "inf": 0.1800, "trn": 0.3300,
}
_ARM_DISCOUNT = 0.80       # arm lines price ~20% under x86 peers
_METAL_PREMIUM = 1.10
_NVME_PREMIUM = 1.12
_NET_PREMIUM = 1.08
_GEN_DISCOUNT = 0.92


def _jitter(seed: str, lo: float, hi: float) -> float:
    h = int.from_bytes(hashlib.sha256(seed.encode()).digest()[:4], "big")
    return lo + (hi - lo) * (h / 0xFFFFFFFF)


# Static seed-price tables (absent until codegen has run once).
try:
    from .zz_generated_pricing import (
        INITIAL_ON_DEMAND_PRICES as _STATIC_OD,
        INITIAL_SPOT_PRICES as _STATIC_SPOT,
    )
except ImportError:
    _STATIC_OD: dict = {}
    _STATIC_SPOT: dict = {}


class PricingProvider:
    """Thread-safe price source; static model + overridable live updates."""

    def __init__(self, isolated_vpc: bool = False, clock=None):
        from ..utils.clock import RealClock

        self._od_overrides: dict[str, float] = {}
        # per-(type, zone) on-demand overrides: AWS on-demand is regional,
        # but the launch-path price comparisons are per-OFFERING (reference
        # iterates Offerings.Available() prices) — a live backend that does
        # report zonal variance must be representable
        self._od_zone_overrides: dict[tuple[str, str], float] = {}
        self._spot_overrides: dict[tuple[str, str], float] = {}
        self._lock = threading.RLock()
        self._seq = 0
        self.isolated_vpc = isolated_vpc
        self._clock = clock or RealClock()
        # staleness observability: wall of the last live refresh per source
        # ("spot" / "on-demand"); empty until a live backend has pushed at
        # least once — a static-catalog process is not "stale", it is
        # static (observe_staleness docstring)
        self._last_refresh: dict[str, float] = {}
        # the attached MarketModel (None = static market). The model never
        # changes query results by itself: its walks arrive through the
        # same update_spot override channel a live backend uses, and its
        # reclaim probabilities are read by the catalog tensor build.
        self.market: Optional["MarketModel"] = None

    # -- static seed tables (codegen output; parity: pricing.go:43 loading
    # the compiled-in zz_generated.pricing_* maps; loaded once) ------------
    def _static_od(self, name: str) -> Optional[float]:
        return _STATIC_OD.get(name)

    def _static_spot(self, name: str, zone: str) -> Optional[float]:
        return _STATIC_SPOT.get(name, {}).get(zone)

    # -- static model ------------------------------------------------------
    def _model_od(self, it: "InstanceType") -> float:
        rate = _BASE_VCPU_RATE.get(it.category, 0.05)
        price = rate * it.vcpus
        price *= _GEN_DISCOUNT ** max(0, it.generation - 5)
        if it.arch == "arm64":
            price *= _ARM_DISCOUNT
        if it.bare_metal:
            price *= _METAL_PREMIUM
        if it.local_nvme_gib:
            price *= _NVME_PREMIUM
        if it.family.endswith("n"):
            price *= _NET_PREMIUM
        if it.gpu_count:
            price += it.gpu_count * {"a10g": 0.60, "a100": 2.45, "h100": 6.90}.get(it.gpu_name, 1.0)
        if it.accelerator_count:
            price += it.accelerator_count * (0.95 if it.accelerator_name == "trainium" else 0.23)
        return round(price, 5)

    # -- queries (parity: OnDemandPrice / SpotPrice) -----------------------
    def on_demand_price(self, it: "InstanceType") -> float:
        with self._lock:
            override = self._od_overrides.get(it.name)
            if override is not None:
                return override
            static = self._static_od(it.name)
            return static if static is not None else self._model_od(it)

    def on_demand_price_zonal(self, it: "InstanceType", zone: str) -> float:
        """Per-(type, zone) on-demand offering price: the zonal override if
        a live backend set one, else the regional price."""
        with self._lock:
            override = self._od_zone_overrides.get((it.name, zone))
            if override is not None:
                return override
        return self.on_demand_price(it)

    def spot_price(self, it: "InstanceType", zone: str) -> float:
        """Zonal spot; default derived from on-demand when no live data
        (parity: pricing.go:141-156 spotPrice fallback)."""
        with self._lock:
            override = self._spot_overrides.get((it.name, zone))
            if override is not None:
                return override
            static = self._static_spot(it.name, zone)
            if static is not None:
                return static
            od = self.on_demand_price(it)
            return round(od * _jitter(f"{it.name}:{zone}", 0.24, 0.44), 5)

    def base_spot_price(self, it: "InstanceType", zone: str) -> float:
        """The UNWALKED spot price: static table / on-demand-derived model,
        live overrides ignored. The MarketModel multiplies this — never the
        override — so repeated ticks compose as ``base x multiplier(tick)``
        instead of compounding drift."""
        with self._lock:
            static = self._static_spot(it.name, zone)
            if static is not None:
                return static
            od = self.on_demand_price(it)
            return round(od * _jitter(f"{it.name}:{zone}", 0.24, 0.44), 5)

    # -- live refresh (parity: UpdateOnDemandPricing / UpdateSpotPricing) --
    def update_on_demand(self, prices: Mapping[str, float]) -> None:
        if self.isolated_vpc:
            return
        with self._lock:
            self._od_overrides.update(prices)
            self._seq += 1
            self._last_refresh["on-demand"] = self._clock.now()

    def update_on_demand_zonal(self, prices: Mapping[tuple[str, str], float]) -> None:
        if self.isolated_vpc:
            return
        with self._lock:
            self._od_zone_overrides.update(prices)
            self._seq += 1
            self._last_refresh["on-demand"] = self._clock.now()

    def update_spot(self, prices: Mapping[tuple[str, str], float]) -> None:
        if self.isolated_vpc:
            return
        with self._lock:
            self._spot_overrides.update(prices)
            self._seq += 1
            self._last_refresh["spot"] = self._clock.now()

    def reset(self) -> None:
        with self._lock:
            self._od_overrides.clear()
            self._od_zone_overrides.clear()
            self._spot_overrides.clear()
            self._last_refresh.clear()
            self._seq += 1

    def seq_num(self) -> int:
        with self._lock:
            return self._seq

    # -- staleness observability (satellite: ISSUE 16) ---------------------
    def observe_staleness(self, ttl_s: float = PRICING_STALE_TTL_S,
                          recorder=None) -> dict[str, float]:
        """Publish ``karpenter_pricing_age_seconds{source}`` for every
        source a live backend has refreshed, and a ``PricingStale``
        Warning event once an age crosses ``ttl_s``. A source that has
        NEVER refreshed is not reported: a static-catalog process runs on
        compiled-in prices by design and must not page. Isolated-VPC mode
        skips live refresh entirely (pricing.go:164-170 parity), so it
        never reports either. Returns ``{source: age_seconds}``."""
        with self._lock:
            if self.isolated_vpc:
                return {}
            now = self._clock.now()
            ages = {src: max(0.0, now - at)
                    for src, at in self._last_refresh.items()}
        from ..metrics import PRICING_AGE

        for src, age in ages.items():
            PRICING_AGE.set(age, source=src)
            if age > ttl_s:
                if recorder is None:
                    from ..events import default_recorder

                    recorder = default_recorder()
                recorder.publish(
                    kind="PricingProvider", name=src, reason="PricingStale",
                    message=(
                        f"{src} pricing last refreshed {age:.0f}s ago "
                        f"(TTL {ttl_s:.0f}s); cost decisions are running "
                        "on stale market data"
                    ),
                    type="Warning",
                )
        return ages


class MarketModel:
    """Seeded, clock-driven market: price-volatility walks and per-offering
    spot-reclaim probability, both PURE functions of
    ``(seed, instance_type, zone, tick)``.

    Determinism contract (the same one faults and traces obey): no ambient
    randomness, no wall time — every draw is a sha256 of the seed and the
    coordinates, and time is the injected clock quantized to ``tick_s``.
    Two models with the same seed therefore produce byte-identical price
    traces, and a resumed run re-derives the identical market at any tick
    (``tests/test_market.py`` pins this across 3 seeds).

    The walk per (type, zone) is a diurnal sinusoid with hashed phase and
    amplitude plus bounded per-tick hash noise — cheap (no state to
    integrate), smooth at the tick scale, and mean-reverting by
    construction. Reclaim probability rises as the walk dips under par:
    cheap spot is crowded spot, which is exactly when AWS reclaims it.

    ``apply()`` pushes the walked spot prices through the SAME
    ``update_spot`` override channel a live pricing backend uses, so
    downstream (tensor build, seqnum cache keys, provenance) cannot tell
    a simulated market from a real one. The reclaim-probability discount
    is folded into price VALUES at tensor build
    (``catalog/provider.py``), never into new jit arguments — tensor
    shapes are untouched and the PR 14 zero-retrace gates hold.
    """

    def __init__(self, seed: int = 0, clock=None, volatility: float = 0.35,
                 reclaim_lambda: float = 0.25, tick_s: float = 300.0,
                 period_s: float = 86400.0):
        from ..utils.clock import RealClock

        self.seed = int(seed)
        self.clock = clock or RealClock()
        self.volatility = float(volatility)
        # $/hr risk premium per unit reclaim probability: effective spot
        # price = spot x (1 + reclaim_lambda x p_reclaim). The expected
        # cost of a reclaim (drain + relaunch + rebind) amortized over the
        # instance's mean life — designs/market-engine.md derives 0.25.
        self.reclaim_lambda = float(reclaim_lambda)
        self.tick_s = float(tick_s)
        self.period_s = float(period_s)

    # -- deterministic draws ----------------------------------------------
    def _u(self, *key) -> float:
        h = hashlib.sha256(
            ":".join(str(k) for k in (self.seed,) + key).encode()
        ).digest()
        return int.from_bytes(h[:8], "big") / float(1 << 64)

    def tick_index(self, now: Optional[float] = None) -> int:
        now = self.clock.now() if now is None else now
        return int(now // self.tick_s)

    # -- the walk ----------------------------------------------------------
    def spot_multiplier(self, name: str, zone: str,
                        now: Optional[float] = None) -> float:
        """Walked price over base price for one offering at ``now``:
        diurnal sine (hashed phase/amplitude per offering) + bounded
        per-tick hash noise, floored at 0.2x."""
        now = self.clock.now() if now is None else now
        phase = self._u("phase", name, zone) * 2.0 * math.pi
        amp = self.volatility * (0.5 + 0.5 * self._u("amp", name, zone))
        base = 1.0 + amp * math.sin(2.0 * math.pi * now / self.period_s + phase)
        t = self.tick_index(now)
        # two-tick average keeps adjacent ticks correlated (a walk, not
        # white noise) while staying a pure function of the tick index
        noise = (
            self._u("noise", name, zone, t)
            + self._u("noise", name, zone, t - 1)
            - 1.0
        ) * self.volatility * 0.5
        return max(0.2, base + noise)

    def reclaim_probability(self, name: str, zone: str,
                            now: Optional[float] = None) -> float:
        """P(reclaim within the pricing horizon) for a spot offering:
        a hashed per-offering base rate, amplified when the walk trades
        under par (cheap spot = crowded pool = reclaim pressure)."""
        now = self.clock.now() if now is None else now
        base = 0.02 + 0.08 * self._u("reclaim", name, zone)
        pressure = max(0.0, 1.0 - self.spot_multiplier(name, zone, now))
        return min(0.9, base + 1.5 * pressure * (0.5 + 0.5 * self._u("sens", name, zone)))

    # -- application --------------------------------------------------------
    def apply(self, catalog) -> int:
        """Push the current tick's walked spot prices into the catalog's
        pricing overrides (the live-refresh channel — seqnums bump, caches
        invalidate, exactly like a real backend). No-op (returns 0) when
        the market kill switch is off, so ``KARPENTER_TPU_MARKET=0`` runs
        never see a walked price."""
        from ..market import market_enabled

        if not market_enabled():
            return 0
        now = self.clock.now()
        updates: dict[tuple[str, str], float] = {}
        for it in catalog.list():
            for o in it.offerings:
                if o.capacity_type != lbl.CAPACITY_TYPE_SPOT:
                    continue
                base = catalog.pricing.base_spot_price(it, o.zone)
                updates[(it.name, o.zone)] = round(
                    base * self.spot_multiplier(it.name, o.zone, now), 5
                )
        if updates:
            catalog.pricing.update_spot(updates)
        return len(updates)
