"""Pricing: $/hr catalog with static seed prices + live-refresh interface.

Reference parity: ``pkg/providers/pricing/pricing.go`` — compiled-in seed
prices (pricing.go:43), on-demand refresh via a pricing API, per-zone spot
prices with an on-demand-derived default (pricing.go:75-90,141-156), and an
isolated-VPC mode that skips live refresh (pricing.go:164-170).

The price *model* is deterministic (a function of the type's shape), standing
in for the reference's generated ``zz_generated.pricing_*.go`` tables. A
``PriceUpdate`` hook lets a live backend override any entry, mirroring
UpdateOnDemandPricing / UpdateSpotPricing.
"""

from __future__ import annotations

import hashlib
import threading
from typing import TYPE_CHECKING, Mapping, Optional

from ..models import labels as lbl

if TYPE_CHECKING:
    from .instancetypes import InstanceType

# Seed $/vcpu-hr by category; generation discount compounds 8%/gen newer than 5.
_BASE_VCPU_RATE = {
    "c": 0.0425, "m": 0.0480, "r": 0.0630, "x": 0.0835,
    "i": 0.0780, "t": 0.0209, "d": 0.0690,
    "g": 0.2500, "p": 0.7500, "inf": 0.1800, "trn": 0.3300,
}
_ARM_DISCOUNT = 0.80       # arm lines price ~20% under x86 peers
_METAL_PREMIUM = 1.10
_NVME_PREMIUM = 1.12
_NET_PREMIUM = 1.08
_GEN_DISCOUNT = 0.92


def _jitter(seed: str, lo: float, hi: float) -> float:
    h = int.from_bytes(hashlib.sha256(seed.encode()).digest()[:4], "big")
    return lo + (hi - lo) * (h / 0xFFFFFFFF)


# Static seed-price tables (absent until codegen has run once).
try:
    from .zz_generated_pricing import (
        INITIAL_ON_DEMAND_PRICES as _STATIC_OD,
        INITIAL_SPOT_PRICES as _STATIC_SPOT,
    )
except ImportError:
    _STATIC_OD: dict = {}
    _STATIC_SPOT: dict = {}


class PricingProvider:
    """Thread-safe price source; static model + overridable live updates."""

    def __init__(self, isolated_vpc: bool = False):
        self._od_overrides: dict[str, float] = {}
        # per-(type, zone) on-demand overrides: AWS on-demand is regional,
        # but the launch-path price comparisons are per-OFFERING (reference
        # iterates Offerings.Available() prices) — a live backend that does
        # report zonal variance must be representable
        self._od_zone_overrides: dict[tuple[str, str], float] = {}
        self._spot_overrides: dict[tuple[str, str], float] = {}
        self._lock = threading.RLock()
        self._seq = 0
        self.isolated_vpc = isolated_vpc

    # -- static seed tables (codegen output; parity: pricing.go:43 loading
    # the compiled-in zz_generated.pricing_* maps; loaded once) ------------
    def _static_od(self, name: str) -> Optional[float]:
        return _STATIC_OD.get(name)

    def _static_spot(self, name: str, zone: str) -> Optional[float]:
        return _STATIC_SPOT.get(name, {}).get(zone)

    # -- static model ------------------------------------------------------
    def _model_od(self, it: "InstanceType") -> float:
        rate = _BASE_VCPU_RATE.get(it.category, 0.05)
        price = rate * it.vcpus
        price *= _GEN_DISCOUNT ** max(0, it.generation - 5)
        if it.arch == "arm64":
            price *= _ARM_DISCOUNT
        if it.bare_metal:
            price *= _METAL_PREMIUM
        if it.local_nvme_gib:
            price *= _NVME_PREMIUM
        if it.family.endswith("n"):
            price *= _NET_PREMIUM
        if it.gpu_count:
            price += it.gpu_count * {"a10g": 0.60, "a100": 2.45, "h100": 6.90}.get(it.gpu_name, 1.0)
        if it.accelerator_count:
            price += it.accelerator_count * (0.95 if it.accelerator_name == "trainium" else 0.23)
        return round(price, 5)

    # -- queries (parity: OnDemandPrice / SpotPrice) -----------------------
    def on_demand_price(self, it: "InstanceType") -> float:
        with self._lock:
            override = self._od_overrides.get(it.name)
            if override is not None:
                return override
            static = self._static_od(it.name)
            return static if static is not None else self._model_od(it)

    def on_demand_price_zonal(self, it: "InstanceType", zone: str) -> float:
        """Per-(type, zone) on-demand offering price: the zonal override if
        a live backend set one, else the regional price."""
        with self._lock:
            override = self._od_zone_overrides.get((it.name, zone))
            if override is not None:
                return override
        return self.on_demand_price(it)

    def spot_price(self, it: "InstanceType", zone: str) -> float:
        """Zonal spot; default derived from on-demand when no live data
        (parity: pricing.go:141-156 spotPrice fallback)."""
        with self._lock:
            override = self._spot_overrides.get((it.name, zone))
            if override is not None:
                return override
            static = self._static_spot(it.name, zone)
            if static is not None:
                return static
            od = self.on_demand_price(it)
            return round(od * _jitter(f"{it.name}:{zone}", 0.24, 0.44), 5)

    # -- live refresh (parity: UpdateOnDemandPricing / UpdateSpotPricing) --
    def update_on_demand(self, prices: Mapping[str, float]) -> None:
        if self.isolated_vpc:
            return
        with self._lock:
            self._od_overrides.update(prices)
            self._seq += 1

    def update_on_demand_zonal(self, prices: Mapping[tuple[str, str], float]) -> None:
        if self.isolated_vpc:
            return
        with self._lock:
            self._od_zone_overrides.update(prices)
            self._seq += 1

    def update_spot(self, prices: Mapping[tuple[str, str], float]) -> None:
        if self.isolated_vpc:
            return
        with self._lock:
            self._spot_overrides.update(prices)
            self._seq += 1

    def reset(self) -> None:
        with self._lock:
            self._od_overrides.clear()
            self._od_zone_overrides.clear()
            self._spot_overrides.clear()
            self._seq += 1

    def seq_num(self) -> int:
        with self._lock:
            return self._seq
