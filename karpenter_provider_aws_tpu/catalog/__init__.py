"""The instance-type catalog: capacities, allocatable math, prices, offerings.

Reference parity: ``pkg/providers/instancetype`` (capacity/overhead math,
offerings x zone x capacity-type, composite seqnum cache key),
``pkg/providers/pricing`` (static seed prices + refresh), and the generated
``zz_generated.*`` data tables (here replaced by a deterministic programmatic
generator — the reference proves the catalog can be data, not API calls).
"""

from .instancetypes import (  # noqa: F401
    InstanceType,
    Offering,
    generate_catalog,
    DEFAULT_ZONES,
    DEFAULT_REGION,
)
from .pricing import PricingProvider  # noqa: F401
from .provider import CatalogProvider, OverheadOptions  # noqa: F401
