"""CloudProvider: the plugin between the control plane and the cloud.

Create() parity with ``pkg/cloudprovider/cloudprovider.go:81-141`` +
``pkg/providers/instance/instance.go:94-258``:
 - nodeclass readiness gate (cloudprovider.go:90-93)
 - ranked instance-type/offering options filtered by the ICE cache
 - image resolution grouping by arch/accelerator (resolver.go:123-162)
 - zonal subnet choice with in-flight IP accounting (subnet.go:133-234)
 - launch via the request-coalescing batcher (createfleet.go:52-110)
 - ICE errors classified into the unavailable-offerings cache
   (instance.go:362-368) and surfaced to the caller
 - instance -> NodeClaim status with labels + capacity
   (cloudprovider.go:294-337 instanceToNodeClaim)
"""

from __future__ import annotations

import enum
import threading
from typing import Optional

from ..catalog.provider import CatalogProvider
from .backend import LaunchRequest
from ..models import labels as lbl
from ..models.nodeclaim import NodeClaim
from ..models.nodeclass import NodeClass
from ..providers.bootstrap import ClusterInfo
from ..providers.images import ImageProvider, resolve_image_for
from ..providers.instanceprofiles import InstanceProfileProvider
from ..providers.launchtemplates import LaunchTemplateProvider
from ..providers.securitygroups import SecurityGroupProvider
from ..providers.subnets import SubnetProvider
from ..utils import errors
from ..utils.batcher import Batcher, BatcherOptions
from ..utils.clock import Clock, RealClock

MANAGED_TAG = "karpenter.tpu/managed"
NODEPOOL_TAG = "karpenter.tpu/nodepool"
# instance.go:52 instanceTypeFlexibilityThreshold: minimum type flexibility
# for a spot->on-demand fallback launch
OD_FALLBACK_FLEXIBILITY_MIN = 5
NODECLAIM_TAG = "karpenter.tpu/nodeclaim"


class DriftReason(str, enum.Enum):
    NONE = ""
    STATIC = "NodeClassHashDrifted"          # hash/controller.go parity
    IMAGE = "ImageDrifted"                   # drift.go AMI drift
    SUBNET = "SubnetDrifted"
    SECURITY_GROUP = "SecurityGroupDrifted"
    NODEPOOL = "NodePoolHashDrifted"         # core NodePool static drift


class CloudProvider:
    def __init__(
        self,
        cloud,
        catalog: CatalogProvider,
        cluster,
        clock: Optional[Clock] = None,
        batcher_options: Optional[BatcherOptions] = None,
        cluster_info: Optional[ClusterInfo] = None,
    ):
        self.cloud = cloud
        self.catalog = catalog
        self.cluster = cluster
        self.clock = clock or RealClock()
        self.cluster_info = cluster_info or ClusterInfo(name="cluster-1")
        self.subnets = SubnetProvider(cloud, clock=clock)
        self.security_groups = SecurityGroupProvider(cloud, clock=clock)
        self.images = ImageProvider(cloud, clock=clock)
        self.instance_profiles = InstanceProfileProvider(cloud, clock=clock)
        self.launch_templates = LaunchTemplateProvider(cloud, self.cluster_info, clock=clock)
        from ..providers.reservations import ReservationProvider

        self.capacity_reservations = ReservationProvider(cloud, clock=clock)
        from ..utils.cache import CacheTTL, TTLCache

        self._launchable_cache = TTLCache(default_ttl=CacheTTL.DEFAULT, clock=clock)
        opts = batcher_options or BatcherOptions()
        self._fleet_batcher: Batcher = Batcher(self.cloud.create_fleet, options=opts)
        # fences stamped by delete() ride beside the coalesced id batch:
        # the batcher's unit is a bare instance id, so the (id -> fence)
        # map travels out of band and is consumed per flushed batch
        self._pending_fences: dict[str, tuple] = {}
        self._fences_lock = threading.Lock()
        self._terminate_batcher: Batcher = Batcher(
            self._terminate_batch,
            options=BatcherOptions(idle_timeout_s=opts.idle_timeout_s * 3,
                                   max_timeout_s=opts.max_timeout_s, max_items=500),
        )

    def _terminate_batch(self, ids: list) -> list:
        """One coalesced TerminateInstances call, carrying each id's
        fencing token when the sharded control plane stamped one and the
        backend can enforce it (the fake / any fenced store); unfenced
        backends get the plain call."""
        with self._fences_lock:
            fences = {
                i: self._pending_fences.pop(i)
                for i in list(ids) if i in self._pending_fences
            }
        if fences:
            import inspect

            try:
                accepts = "fences" in inspect.signature(
                    self.cloud.terminate_instances
                ).parameters
            except (TypeError, ValueError):
                accepts = False
            if accepts:
                return self.cloud.terminate_instances(list(ids), fences=fences)
        return self.cloud.terminate_instances(list(ids))

    # -- Create ------------------------------------------------------------
    def create(self, claim: NodeClaim) -> NodeClaim:
        nodeclass = self.cluster.nodeclasses.get(claim.nodeclass_name)
        if nodeclass is None:
            raise errors.NotFoundError(f"nodeclass {claim.nodeclass_name} not found")
        if not nodeclass.status.is_ready():
            raise errors.CloudError(
                f"nodeclass {nodeclass.name} is not ready", code="NodeClassNotReady"
            )

        type_options = [
            self.catalog.get(n) for n in claim.instance_type_options if self.catalog.get(n)
        ]
        if not type_options:
            raise errors.CloudError("no instance type options", code="NoInstanceTypes")

        # Image grouping: resolve for the best-ranked type, then keep only
        # types the same image serves (arch/gpu grouping parity).
        images = self.images.list(nodeclass)
        # First ranked option with a resolvable image wins; options no image
        # maps to are dropped rather than failing the launch (parity:
        # resolver.go:123-162 — types with no AMI never reach CreateFleet).
        image = None
        for t in type_options:
            image = resolve_image_for(images, t)
            if image is not None:
                break
        if image is None:
            raise errors.CloudError(
                f"no image for any of {[t.name for t in type_options[:5]]}",
                code="NoCompatibleImage",
            )
        type_options = [
            t for t in type_options if resolve_image_for(images, t) is image
        ]

        # ICE-masked offering options (parity: offerings filtered against the
        # unavailable cache before launch).
        offerings = list(self._live_offerings(claim, [t.name for t in type_options]))
        if not offerings:
            raise errors.InsufficientCapacityError(
                message="all candidate offerings are ICE-cached"
            )

        # Mixed-captype launches drop spot types costlier than the cheapest
        # ATTAINABLE on-demand type (parity: instance.go:429-451
        # filterUnwantedSpot) — the fleet's lowest-price walk could otherwise
        # land on a bigger spot box when the best-ranked type's offering is
        # ICE-masked and a cheap on-demand one would have served. Dropping
        # types invalidates the offering ranking (it is priced against the
        # best-ranked type) and can retire pairs only the dropped types kept
        # alive, so the offerings are recomputed from the survivors.
        filtered = self._filter_unwanted_spot(type_options, offerings)
        if filtered is not type_options:
            type_options = filtered
            offerings = list(
                self._live_offerings(claim, [t.name for t in type_options])
            )
            if not offerings:
                raise errors.InsufficientCapacityError(
                    message="all candidate offerings are ICE-cached"
                )

        zones = sorted({z for z, _ in offerings})
        # ONE discovery snapshot drives both the zonal pick and the
        # public-IP inference: a cache expiry between two reads could pin
        # associatePublicIP=False onto a launch into a public subnet.
        subnet_snapshot = self.subnets.list(nodeclass)
        subnet_by_zone = self.subnets.zonal_subnets_for_launch(
            nodeclass, zones, subnets=subnet_snapshot
        )
        offerings = [o for o in offerings if o[0] in subnet_by_zone]
        if not offerings:
            raise errors.CloudError("no subnet available in candidate zones", code="NoSubnets")
        sgs = tuple(g.id for g in self.security_groups.list(nodeclass))

        # On-demand fallback flexibility gate (parity: instance.go:270-289
        # checkODFallback): spot was allowed but every offering that
        # actually remains launchable (post-ICE, post-subnet) is on-demand —
        # launching that fallback with almost no type flexibility risks
        # immediate ICE churn, so the reference refuses below 5 options and
        # so do we. Reserved (pre-paid) launches are exempt.
        # UNION of solve-time live offerings and the claim's capacity-type
        # requirements: if spot was ICE-cached at solve time the offerings
        # carry only on-demand, but the claim's requirements still allow
        # spot — the reference derives this gate from the requirements
        # (instance.go:272), so the fallback check must still fire.
        allowed_cts = {ct for _, ct in (claim.offering_options or ())} | set(
            claim.capacity_type_options or ()
        )
        live_cts = {ct for _, ct in offerings}
        if (
            lbl.CAPACITY_TYPE_SPOT in allowed_cts
            and lbl.CAPACITY_TYPE_SPOT not in live_cts
            and lbl.CAPACITY_TYPE_ON_DEMAND in live_cts
            and lbl.CAPACITY_TYPE_RESERVED not in live_cts
            and len(type_options) < OD_FALLBACK_FLEXIBILITY_MIN
        ):
            raise errors.CloudError(
                f"at least {OD_FALLBACK_FLEXIBILITY_MIN} instance types are "
                "recommended when flexible to spot but falling back to "
                f"on-demand; this launch has {len(type_options)}",
                code="InsufficientTypeFlexibility",
            )

        # Ensure the launch template for this image group (parity:
        # launchtemplate.EnsureAll at instance.go launch time).
        def ensure_template() -> str:
            pool = self.cluster.nodepools.get(claim.nodepool_name)
            return self.launch_templates.ensure_all(
                nodeclass,
                [(image, type_options)],
                labels=dict(claim.labels),
                taints=list(claim.taints) + list(claim.startup_taints),
                kubelet=getattr(pool, "kubelet", None) if pool else None,
                # the user's explicit setting wins (ec2nodeclass.go:45-47);
                # otherwise explicit False only when every resolved subnet
                # is known private (subnet.go:119-130); same snapshot as
                # the zonal pick above
                associate_public_ip=(
                    nodeclass.associate_public_ip
                    if nodeclass.associate_public_ip is not None
                    else self.subnets.associate_public_ip_value(
                        nodeclass, subnets=subnet_snapshot
                    )
                ),
            )[image.id]

        lt_name = ensure_template()
        # Fencing (sharded control plane): name the lease tenancy that
        # sanctioned this launch — the ambient sanction key (a disruption
        # replacement is sanctioned by the OLD node's partition lease),
        # else the GLOBAL lease (provisioning). () when unsharded.
        from ..operator import sharding

        fence = sharding.write_fence(self.cluster, claim) or ()
        request = LaunchRequest(
            fence=tuple(fence),
            instance_type_options=[t.name for t in type_options],
            offering_options=offerings,
            image_id=image.id,
            subnet_by_zone=subnet_by_zone,
            security_group_ids=sgs,
            context=nodeclass.context,
            tags={
                MANAGED_TAG: "true",
                NODEPOOL_TAG: claim.nodepool_name,
                NODECLAIM_TAG: claim.name,
                **nodeclass.tags,
            },
            launch_template_name=lt_name,
        )
        try:
            try:
                result = self._fleet_batcher.add(request)
            except errors.CloudError as e:
                if not errors.is_launch_template_not_found(e):
                    raise
                # Single retry after re-ensuring the template (parity:
                # instance.go:106-110 LT-not-found retry).
                self.launch_templates.invalidate(lt_name)
                request.launch_template_name = ensure_template()
                result = self._fleet_batcher.add(request)
        except Exception as e:
            # give back every pre-deducted IP, then classify ICE into the
            # unavailable cache so the next solve masks the offering
            self.subnets.release_unused(subnet_by_zone, used_zone="")
            if errors.is_unfulfillable_capacity(e) and getattr(e, "instance_type", ""):
                self.catalog.unavailable.mark_unavailable(
                    e.instance_type, e.zone, e.capacity_type
                )
                from ..metrics import ICE_EVENTS

                ICE_EVENTS.inc(capacity_type=e.capacity_type)
            raise
        self.subnets.release_unused(subnet_by_zone, result.zone)
        return self._instance_to_claim(claim, result, nodeclass)

    def _filter_unwanted_spot(self, type_options, offerings):
        """During a MIXED capacity-type launch, drop candidate types whose
        cheapest live offering is costlier than the cheapest on-demand
        price among the candidates (parity: instance.go:429-451
        filterUnwantedSpot). Spot-only or on-demand-only launches pass
        through untouched, and the cheapest-on-demand type itself always
        survives, so the result is never empty."""
        spot_zones = [z for z, ct in offerings if ct == lbl.CAPACITY_TYPE_SPOT]
        od_zones = [z for z, ct in offerings if ct == lbl.CAPACITY_TYPE_ON_DEMAND]
        has_reserved = any(ct == lbl.CAPACITY_TYPE_RESERVED for _, ct in offerings)
        # Reserved (pre-paid, marginal price 0) launches are exempt: the
        # price comparison below only understands the spot/on-demand market,
        # and dropping the reservation's own type would forfeit the slot.
        if not spot_zones or not od_zones or has_reserved:
            return type_options
        unavailable = self.catalog.unavailable.is_unavailable

        def live_od(t):
            # the comparison floor must be ATTAINABLE: an ICE-cached
            # on-demand price is not a price anyone can launch at, and the
            # price compared is the cheapest per-(type, zone) OFFERING
            # price over live zones (reference computes over
            # Offerings.Available(), per-offering prices) — not one
            # zone-independent number per type.
            return min(
                (
                    self.catalog.pricing.on_demand_price_zonal(t, z)
                    for z in od_zones
                    if not unavailable(t.name, z, lbl.CAPACITY_TYPE_ON_DEMAND)
                ),
                default=float("inf"),
            )

        cheapest_od = min((live_od(t) for t in type_options), default=float("inf"))
        if cheapest_od == float("inf"):
            return type_options  # no attainable on-demand: nothing to compare

        def cheapest_live(t):
            best = live_od(t)
            for z in spot_zones:
                if not unavailable(t.name, z, lbl.CAPACITY_TYPE_SPOT):
                    best = min(best, self.catalog.pricing.spot_price(t, z))
            return best

        kept = [t for t in type_options if cheapest_live(t) <= cheapest_od + 1e-9]
        if len(kept) == len(type_options):
            return type_options  # identity signals "nothing dropped" to the caller
        return kept or type_options

    def launchable_type_names(self, nodepool) -> "Optional[set[str]]":
        """Types a nodepool's nodeclass can actually boot: at least one
        resolved image is compatible (arch + accelerator). None = no
        constraint known (nodeclass missing/unready — the readiness gate
        rejects the launch anyway). Fed into the solve so the scheduler
        never commits capacity it cannot image (parity: amifamily
        MapToInstanceTypes, ami.go:79-90)."""
        nodeclass = self.cluster.nodeclasses.get(nodepool.nodeclass_name)
        if nodeclass is None or not nodeclass.status.is_ready():
            return None
        images = self.images.list(nodeclass)
        key = ("launchable", nodeclass.name, tuple(i.id for i in images), self.catalog.cache_key())
        hit = self._launchable_cache.get(key)
        if hit is not None:
            return hit
        allowed = {
            t.name for t in self.catalog.list() if resolve_image_for(images, t) is not None
        }
        self._launchable_cache.set(key, allowed)
        return allowed

    def _live_offerings(self, claim: NodeClaim, type_names):
        """(zone, captype) pairs from the claim not ICE-masked for at least
        one candidate type, ranked cheapest-first by the best-ranked type's
        actual offering price — the fleet takes the first launchable pair, so
        this ordering IS the lowest-price allocation strategy. A launch that
        lands anywhere but the cheapest live offering would immediately look
        consolidatable again (replace churn)."""
        pairs = claim.capacity_type_options or [lbl.CAPACITY_TYPE_ON_DEMAND]
        zones = claim.zone_options or list(self.catalog.zones)
        joint = getattr(claim, "offering_options", None) or [
            (z, ct) for z in zones for ct in pairs
        ]
        it = self.catalog.get(type_names[0]) if type_names else None
        # clock-gated reservation windows: an expired/not-yet-open capacity
        # block must not rank (or pass the filter) as free capacity
        now = self.clock.now()

        def price(offer):
            zone, captype = offer
            if it is None:
                return 0.0
            if captype == lbl.CAPACITY_TYPE_RESERVED:
                # pre-paid: marginal cost 0 while count remains, else
                # unusable (skipped below too)
                has = self.catalog.reservations.remaining(it.name, zone, now=now) > 0
                return 0.0 if has else float("inf")
            if captype == lbl.CAPACITY_TYPE_SPOT:
                return self.catalog.pricing.spot_price(it, zone)
            return self.catalog.pricing.on_demand_price(it)

        for zone, captype in sorted(joint, key=price):
            if captype == lbl.CAPACITY_TYPE_RESERVED and not any(
                self.catalog.reservations.remaining(t, zone, now=now) > 0
                for t in type_names
            ):
                continue
            if any(
                not self.catalog.unavailable.is_unavailable(t, zone, captype)
                for t in type_names
            ):
                yield (zone, captype)

    def _instance_to_claim(self, claim: NodeClaim, inst, nodeclass: NodeClass) -> NodeClaim:
        it = self.catalog.get(inst.instance_type)
        claim.status.provider_id = inst.provider_id
        claim.status.image_id = inst.image_id
        pool = self.cluster.nodepools.get(claim.nodepool_name)
        kubelet = getattr(pool, "kubelet", None) if pool else None
        max_pods = kubelet.max_pods if kubelet is not None else None
        # ephemeral-storage follows the nodeclass: root EBS volume size, or
        # the total instance store under the RAID0 policy (types.go:218-244)
        cap_kw = nodeclass.capacity_kwargs()
        claim.status.capacity = it.capacity(max_pods=max_pods, **cap_kw)
        claim.status.allocatable = self.catalog.allocatable(
            it, max_pods=max_pods, **cap_kw
        )
        claim.labels.update(it.labels())
        claim.labels[lbl.TOPOLOGY_ZONE] = inst.zone
        claim.labels[lbl.CAPACITY_TYPE] = inst.capacity_type
        zone_types = self._zone_types()
        if zone_types:
            claim.labels[lbl.ZONE_TYPE] = zone_types.get(inst.zone, "availability-zone")
        claim.status.internal_ip = getattr(inst, "private_ip", "")
        reservation_id = getattr(inst, "capacity_reservation_id", "")
        if reservation_id:
            claim.labels[lbl.CAPACITY_RESERVATION_ID] = reservation_id
            # keep the catalog's in-flight view fresh between status
            # refreshes — target the reservation the cloud actually drew
            self.catalog.reservations.consume_id(reservation_id)
            # a cached discovery snapshot now under-counts `used`: drop it so
            # the next status reconcile re-describes instead of rolling the
            # in-flight accounting back
            self.capacity_reservations.reset()
        claim.labels[lbl.NODEPOOL] = claim.nodepool_name
        claim.annotations.update(nodeclass.hash_annotations())
        claim.created_at = self.clock.now()
        claim.finalizers.add("karpenter.tpu/termination")
        claim.status.set_condition("Launched", True)
        return claim

    # -- Delete / Get / List ----------------------------------------------
    def delete(self, claim: NodeClaim) -> None:
        instance_id = parse_provider_id(claim.status.provider_id)
        if instance_id is None:
            raise errors.NotFoundError(f"claim {claim.name} has no provider id")
        from ..operator import sharding

        fence = sharding.write_fence(self.cluster, claim)
        if fence is not None:
            with self._fences_lock:
                self._pending_fences[instance_id] = tuple(fence)
        self._terminate_batcher.add(instance_id)
        # Return pre-paid capacity to the in-flight view — but only once the
        # cloud confirms the instance is actually terminated. Releasing on
        # the API call alone would advertise the slot while the instance is
        # still shutting down; an immediate relaunch would then ICE and
        # blacklist the reserved offering for the whole ICE TTL. If the
        # instance is still draining, the status reconcile re-syncs counts
        # from the cloud once it lands. The label is popped so a retried
        # delete can't double-release.
        rid = claim.labels.get(lbl.CAPACITY_RESERVATION_ID)
        if rid:
            try:
                terminated = self.cloud.get_instance(instance_id).state == "terminated"
            except errors.NotFoundError:
                terminated = True  # instance already gone
            except Exception:
                # A transient describe error (throttle, injected fault) says
                # nothing about instance state — keep the label so a retried
                # delete re-confirms, and let the status reconcile re-sync
                # counts. Releasing here would over-advertise the reserved
                # offering and invite an ICE blacklist.
                terminated = False
            if terminated:
                claim.labels.pop(lbl.CAPACITY_RESERVATION_ID, None)
                self.catalog.reservations.release(rid)
                self.capacity_reservations.reset()  # stale snapshot over-counts now

    def _zone_types(self) -> dict:
        """zone -> availability-zone|local-zone via the cloud's describe API
        (DescribeAvailabilityZones analogue), TTL-cached — zone topology
        changes at region-buildout cadence, not per launch."""
        hit = self._launchable_cache.get("zone-types")
        if hit is not None:
            return hit
        describe = getattr(self.cloud, "describe_availability_zones", None)
        out = describe() if describe is not None else {}
        self._launchable_cache.set("zone-types", out)
        return out

    def pool_reserved_allowed(self, nodepool) -> "set[tuple[str, str]]":
        """The (instance_type, zone) reserved offerings this pool may use:
        exactly its own nodeclass's resolved reservations. Per-pair — not a
        boolean — because the catalog tensors advertise every nodeclass's
        reservations globally, and a pool holding reservation X must not
        drain another nodeclass's reservation Y. Both the provisioner and
        the consolidation replace path gate through this one predicate so
        the two can never drift apart."""
        nc = self.cluster.nodeclasses.get(nodepool.nodeclass_name)
        if nc is None:
            return set()
        return {
            (r.instance_type, r.zone)
            for r in getattr(nc.status, "capacity_reservations", []) or []
        }

    def close(self) -> None:
        """Join the batchers' worker pools (their ThreadPoolExecutor threads
        are non-daemon; a stuck wire call would otherwise pin interpreter
        exit). Wired into Operator.stop()."""
        self._fleet_batcher.close()
        self._terminate_batcher.close()

    def reset_caches(self) -> None:
        """Test-environment hook: drop every provider-side cache."""
        self.subnets.reset()
        self.security_groups.reset()
        self.images.reset()
        self.instance_profiles.reset()
        self.launch_templates.reset()
        self.capacity_reservations.reset()
        self._launchable_cache.flush()

    def get(self, provider_id: str):
        instance_id = parse_provider_id(provider_id)
        if instance_id is None:
            raise errors.NotFoundError(f"bad provider id {provider_id}")
        return self.cloud.get_instance(instance_id)

    def list_instances(self):
        """All managed, non-terminated instances (parity: instance.go List
        by karpenter tag)."""
        return self.cloud.list_instances({MANAGED_TAG: "true"})

    # -- GetInstanceTypes --------------------------------------------------
    def get_instance_types(self, nodepool) -> list:
        """The scheduler's device catalog for one nodepool (parity:
        cloudprovider.go:154-171); the heavy lifting is the catalog tensor
        cache keyed by seqnums."""
        return self.catalog.list()

    # -- IsDrifted ---------------------------------------------------------
    def is_drifted(self, claim: NodeClaim, instances=None,
                   discovery_cache=None) -> DriftReason:
        """``instances`` (id -> instance) lets a bulk caller (the
        disruption controller's per-pass drift sweep) resolve the running
        instance from ONE list call instead of a locked per-claim
        ``get()`` round trip — 5k claims paid 5k cloud lookups per pass.
        ``discovery_cache`` (a dict the bulk caller owns for ONE sweep)
        memoizes the per-NODECLASS image/subnet/security-group discovery
        sets the same way: resolving them per claim was ~200ms of a
        10k-node pass for answers identical within the sweep."""
        # NodePool template drift first: the pool the claim was stamped
        # from has since changed labels/taints/requirements (core static
        # drift). Independent of the nodeclass — a deleted nodeclass must
        # not mask it (e.g. the pool was re-pointed and the old class
        # removed, which is itself template drift).
        def _hash_of(obj, kind: str) -> str:
            # spec hashes serialize the whole template (deepcopy + JSON);
            # per-sweep memoization via the caller's cache turns an
            # O(claims) re-serialization per pass into one per pool/class
            if discovery_cache is None:
                return obj.hash()
            hkey = (kind, obj.name)
            h = discovery_cache.get(hkey)
            if h is None:
                h = discovery_cache[hkey] = obj.hash()
            return h

        pool = self.cluster.nodepools.get(claim.nodepool_name)
        pool_stamp = claim.annotations.get(lbl.ANNOTATION_NODEPOOL_HASH)
        if pool is not None and pool_stamp is not None \
                and pool_stamp != _hash_of(pool, "pool"):
            return DriftReason.NODEPOOL
        nodeclass = self.cluster.nodeclasses.get(claim.nodeclass_name)
        if nodeclass is None:
            return DriftReason.NONE
        # static drift: stamped hash vs current spec hash (drift.go:41-60)
        stamped = claim.annotations.get(lbl.ANNOTATION_NODECLASS_HASH)
        if stamped is not None and stamped != _hash_of(nodeclass, "nodeclass"):
            return DriftReason.STATIC
        inst = None
        if instances is not None:
            iid = parse_provider_id(claim.status.provider_id)
            inst = instances.get(iid) if iid else None
        if inst is None:
            # bulk-map miss falls back to the exact per-claim lookup: the
            # listing is tag-filtered, and an untagged-but-running instance
            # must not silently stop drift-checking (misses are rare, so
            # the bulk win survives)
            try:
                inst = self.get(claim.status.provider_id)
            except Exception:
                return DriftReason.NONE
        # image drift: running image no longer among resolved images;
        # subnet / security-group drift vs current discovery. Resolved
        # once per nodeclass when the sweep hands in a cache.
        discovered = (
            discovery_cache.get(nodeclass.name)
            if discovery_cache is not None else None
        )
        if discovered is None:
            discovered = (
                {i.id for i in self.images.list(nodeclass)},
                {s.id for s in self.subnets.list(nodeclass)},
                {g.id for g in self.security_groups.list(nodeclass)},
            )
            if discovery_cache is not None:
                discovery_cache[nodeclass.name] = discovered
        images, subnet_ids, sg_ids = discovered
        if images and inst.image_id not in images:
            return DriftReason.IMAGE
        if inst.subnet_id and inst.subnet_id not in subnet_ids:
            return DriftReason.SUBNET
        if inst.security_group_ids and not set(inst.security_group_ids) <= sg_ids:
            return DriftReason.SECURITY_GROUP
        return DriftReason.NONE


def parse_provider_id(provider_id: str) -> Optional[str]:
    """cloud:///zone/i-... -> i-... (parity: utils.go:26-40 ParseInstanceID)."""
    if not provider_id:
        return None
    parts = provider_id.rsplit("/", 1)
    return parts[-1] if parts[-1].startswith("i-") else None
