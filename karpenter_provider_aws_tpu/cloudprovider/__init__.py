"""The cloud-provider plugin: NodeClaim -> instance lifecycle.

Reference parity: ``pkg/cloudprovider/cloudprovider.go`` (Create / Delete /
Get / List / GetInstanceTypes / IsDrifted) + ``pkg/providers/instance``
(ranked-offering launch, ICE feedback, batched fleet calls).
"""

from .cloudprovider import CloudProvider, DriftReason  # noqa: F401
