"""The cloud-backend contract: what a cloud must provide to run this
framework.

The reference's whole design pivots on a declared plugin boundary
(`/root/reference/pkg/cloudprovider/cloudprovider.go:54` asserts the
interface; the EC2 API surface the providers consume is the implicit second
boundary). Here that second boundary is explicit: ``CloudBackend`` is the
complete call surface the production providers/controllers make against the
cloud, and ``LaunchRequest`` is the wire unit of the launch path. The
in-memory test double (``fake.cloud.FakeCloud``) implements this Protocol;
a real adapter (REST/gRPC) slots in without touching any caller.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Protocol, runtime_checkable


@dataclass
class LaunchRequest:
    """One logical single-node launch; the batcher coalesces many of these
    into one fleet call (parity: createfleet.go:52-110)."""

    instance_type_options: list[str]          # ranked cheapest-first
    offering_options: list[tuple[str, str]]   # launchable (zone, captype)
    image_id: str
    subnet_by_zone: dict[str, str] = field(default_factory=dict)
    security_group_ids: tuple[str, ...] = ()
    tags: dict[str, str] = field(default_factory=dict)
    launch_template_name: str = ""            # "" = launch without a template
    # reserved EC2 launch context, verbatim pass-through (instance.go:220)
    context: str = ""
    # sharded-control-plane fencing (operator/sharding.py): the
    # (lease name, token) tuple naming the lease tenancy that sanctioned
    # this launch. () = unfenced (single-replica). The backend rejects a
    # token older than the lease's current tenancy (StaleFencingToken).
    fence: tuple = ()


@runtime_checkable
class CloudBackend(Protocol):
    """Everything the framework calls on the cloud, in one place.

    Parity map (reference API clients the providers wrap):
     - fleet/instances  -> EC2 CreateFleet / DescribeInstances /
       TerminateInstances / CreateTags (instance.go, tagging controller)
     - subnets/SGs      -> DescribeSubnets / DescribeSecurityGroups
       (subnet.go:75-117, securitygroup.go)
     - images           -> DescribeImages (amifamily/ami.go:176-199)
     - launch templates -> Create/Describe/DeleteLaunchTemplate
       (launchtemplate.go:202-312)
     - instance profile -> IAM Create/DeleteInstanceProfile
       (instanceprofile.go:60-105)
     - reservations     -> DescribeCapacityReservations
     - zones            -> DescribeAvailabilityZones (localzone suite)
    """

    # -- capacity ----------------------------------------------------------
    def create_fleet(self, requests: list[LaunchRequest]) -> list: ...

    def describe_instances(self, ids: list[str]) -> list: ...

    def list_instances(self, tag_filters: Optional[dict[str, str]] = None) -> list: ...

    def terminate_instances(self, ids: list[str]) -> list: ...

    def get_instance(self, instance_id: str): ...

    def tag_instance(self, instance_id: str, tags: dict[str, str]) -> None: ...

    # -- coordination ------------------------------------------------------
    # Leader-election lease host (parity: the coordination.k8s.io Lease the
    # reference's controller-runtime manager uses, cmd/controller/main.go:34).
    # try_acquire_lease is a CAS acquire-or-renew returning the holder AFTER
    # the attempt; release_lease is the voluntary hand-off.
    def try_acquire_lease(self, name: str, holder: str, ttl_s: float) -> str: ...

    def release_lease(self, name: str, holder: str) -> None: ...

    # Fenced coordination (sharded control plane, operator/sharding.py):
    # the CAS additionally returns a monotonic fencing token (bumped per
    # holder change, never per renew) + the holder's instance nonce, and
    # list_leases serves membership discovery. Backends that cannot host
    # fenced leases simply don't run the sharded elector — the single
    # LeaderElector path needs only the two methods above.
    def try_acquire_lease_fenced(
        self, name: str, holder: str, ttl_s: float, nonce: str = "",
    ) -> tuple[str, int, str]: ...

    def list_leases(self, prefix: str = "") -> dict: ...

    # -- networking / discovery -------------------------------------------
    def describe_availability_zones(self) -> dict[str, str]: ...

    # Cluster network facts: at least service_ipv4_cidr / service_ipv6_cidr
    # (parity: EKS DescribeCluster feeding launchtemplate.go:429-450
    # ResolveClusterCIDR).
    def describe_cluster(self) -> dict: ...

    def describe_subnets(self) -> list: ...

    def describe_security_groups(self) -> list: ...

    def describe_capacity_reservations(self) -> list: ...

    # ``selector_terms`` (optional SelectorTerm sequence) lets the backend
    # push discovery scoping into the wire call (AWS: per-term
    # DescribeImages filters/ids/owners); None = account-wide discovery.
    def describe_images(self, selector_terms=None) -> list: ...

    # -- launch templates --------------------------------------------------
    def create_launch_template(self, name: str, image_id: str, user_data: str = "",
                               **kwargs) -> None: ...

    def describe_launch_templates(self) -> list: ...

    def delete_launch_template(self, name: str) -> None: ...

    # -- identity ----------------------------------------------------------
    def create_instance_profile(self, name: str, role: str, tags: dict[str, str]) -> None: ...

    def delete_instance_profile(self, name: str) -> None: ...
