"""Metrics decorator for the CloudProvider plugin boundary.

Parity: ``cmd/controller/main.go:44`` ``metrics.Decorate(cloudProvider)`` —
every plugin method is wrapped with a duration histogram and an error
counter labeled by method, so controller dashboards see provider latency
and failure rates without any provider knowing about metrics.
"""

from __future__ import annotations

import time

from ..metrics import REGISTRY

METHOD_DURATION = REGISTRY.histogram(
    "karpenter_cloudprovider_duration_seconds",
    "CloudProvider method latency",
    buckets=(0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0),
)
METHOD_ERRORS = REGISTRY.counter(
    "karpenter_cloudprovider_errors_total",
    "CloudProvider method errors",
)

_DECORATED = (
    "create",
    "delete",
    "get",
    "list_instances",
    "get_instance_types",
    "is_drifted",
)


class MetricsCloudProvider:
    """Transparent wrapper: decorated methods observe; everything else
    (providers, catalog, caches) proxies straight through."""

    def __init__(self, inner):
        self._inner = inner

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if name not in _DECORATED or not callable(attr):
            return attr

        def timed(*args, **kwargs):
            t0 = time.perf_counter()
            try:
                return attr(*args, **kwargs)
            except Exception as e:
                METHOD_ERRORS.inc(method=name, error=type(e).__name__)
                raise
            finally:
                METHOD_DURATION.observe(time.perf_counter() - t0, method=name)

        return timed


def decorate(cloudprovider) -> MetricsCloudProvider:
    return MetricsCloudProvider(cloudprovider)
