"""Live steady-state regression sentinel + the cliff-detector core.

PR 8's cliff detector found super-linear tails by sweeping the simulator
across scale tiers — attribution AFTER the fact, inside the harness. The
ROADMAP's 1M-node climb needs the same judgment on the live fleet, on
the way up: when a subsystem's share of the steady-state tick jumps or a
tick goes super-linear against its own rolling baseline, the operator
should get a Warning event that NAMES the subsystem — not a dashboard
they have to already be watching.

Two layers, one file:

- :func:`detect_cliffs` — the pure tier-comparison function, LIFTED here
  from ``sim/cliffs.py`` (which now imports it back) so the simulator's
  offline sweep and the live sentinel share one set of thresholds and
  one definition of "super-linear".
- :class:`SteadyStateSentinel` — the live half. A process-wide streaming
  :class:`~..trace.export.SpanAggregator` (installed once, like the
  metrics bridge) accumulates every finished span; each sentinel
  ``tick()`` (driven on the liveness cadence through ``Obs.tick``) diffs
  the cumulative profile against its own cursor, folds the delta into
  per-subsystem shares, and maintains an EWMA + bounded-p99 baseline of
  both the shares and the total tick wall. After a warmup, a share jump
  past the cliff thresholds or a tick blowing past the wall ratio raises
  an **edge-triggered** ``SteadyStateRegression`` Warning event naming
  the subsystem, bumps ``karpenter_sentinel_regressions_total``, and
  lands in ``findings`` (what ``/debug/sentinel`` and the fleet report's
  wall plane serve).

Sentinel readings are WALL-time measurements: deterministic harnesses
(the fleet simulator's byte-identical-report contract) keep findings in
the report's unsigned ``wall`` plane and set ``publish_events = False``
so a slow CI machine can never perturb the signed event stream.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional

# -- shared thresholds (the cliff detector's, unchanged from sim/cliffs) ----
#: defaults, chosen loose enough that measurement noise at small tiers
#: does not page and tight enough that a real N^2 blowup cannot hide
WALL_EXPONENT = 1.35          # allowed wall growth ~ scale ** exponent
WALL_FLOOR_S = 1.0            # ignore wall deltas below this (noise)
BURN_FLOOR = 1.0              # a burn below sustainable never flags
BURN_RATIO = 2.0              # ...and must at least double tier-to-tier
SHARE_JUMP_ABS = 0.10         # +10 percentage points of the profile
SHARE_JUMP_REL = 1.5          # and 1.5x its previous share

# -- live-sentinel tuning ----------------------------------------------------
# Floors mirror the offline detector's noise immunity (WALL_FLOOR_S):
# a share jump inside a sub-second tick is burst texture, not a cliff —
# the PR 10 50k finding was disruption claiming SECONDS of tick wall.
WARMUP_TICKS = 5              # baseline samples before the sentinel judges
EWMA_ALPHA = 0.2              # rolling-baseline smoothing
TICK_WALL_RATIO = 3.0         # a tick this many times its EWMA is a finding
TICK_WALL_FLOOR_MS = 1000.0   # ...if it also grew by at least this much
SHARE_FLOOR_MS = 20.0         # ignore share math on near-empty ticks
FAMILY_FLOOR_MS = 500.0       # a share jump must also BE this much wall
P99_WINDOW = 128              # bounded tick-wall history for the p99 gauge
FINDINGS_CAP = 256
#: a tick whose jit.compile spans carry at least this much wall is not
#: steady state: the compile inflates its owner's share and the total
#: tick, and compile judgment belongs to the RETRACE sentinel (which
#: pages on repetition, not on one ladder-growth compile) — the
#: steady-state judgments skip such ticks instead of paging on a benign
#: one-off spike that only stands out BECAUSE steady ticks got cheap
COMPILE_GRACE_MS = 250.0


def span_family(name: str) -> str:
    """The attribution family a span name folds into: ``controller.*``
    spans keep their full name (the finding must NAME the controller),
    everything else folds to its first segment (solve / consolidate /
    aws / ...). One rule shared by the live sentinel and the simulator's
    tier rows."""
    family = name.split(".", 1)[0] if "." in name else name
    return name if family == "controller" else family


def detect_cliffs(rows: list[dict],
                  wall_exponent: float = WALL_EXPONENT,
                  wall_floor_s: float = WALL_FLOOR_S,
                  burn_floor: float = BURN_FLOOR,
                  burn_ratio: float = BURN_RATIO,
                  share_jump_abs: float = SHARE_JUMP_ABS,
                  share_jump_rel: float = SHARE_JUMP_REL) -> dict:
    """Pure comparison over tier rows (sorted by ``tier`` ascending).

    Returns ``{"cliff_tier": first flagged tier or None,
    "findings": [...]}`` — each finding names the tier, the metric, and
    the evidence (previous vs current value and the allowed bound).
    Formerly ``sim.cliffs.detect_cliffs``; the simulator re-exports it."""
    rows = sorted(rows, key=lambda r: r["tier"])
    findings: list[dict] = []
    for prev, cur in zip(rows, rows[1:]):
        k = cur["tier"] / prev["tier"] if prev["tier"] else 1.0
        # wall growth vs scale growth
        w0 = prev.get("wall_per_sim_hour_s") or 0.0
        w1 = cur.get("wall_per_sim_hour_s") or 0.0
        bound = w0 * (k ** wall_exponent)
        if w0 > 0 and w1 - bound > wall_floor_s:
            findings.append({
                "tier": cur["tier"], "kind": "wall-superlinear",
                "detail": (
                    f"wall/sim-hour {w0:g}s -> {w1:g}s at {k:g}x scale "
                    f"(allowed <= {bound:.2f}s = prev * {k:g}^{wall_exponent})"
                ),
            })
        # SLO burn regression
        b0 = prev.get("slo_worst_burn") or 0.0
        b1 = cur.get("slo_worst_burn") or 0.0
        if b1 > burn_floor and b1 > max(b0 * burn_ratio, b0 + burn_floor):
            findings.append({
                "tier": cur["tier"], "kind": "slo-burn-regression",
                "detail": (
                    f"worst burn {b0:g} -> {b1:g} "
                    f"(floor {burn_floor:g}, ratio {burn_ratio:g}x)"
                ),
            })
        # attribution share shift
        for family in sorted(set(prev.get("shares", {}))
                             | set(cur.get("shares", {}))):
            s0 = prev.get("shares", {}).get(family, 0.0)
            s1 = cur.get("shares", {}).get(family, 0.0)
            if s1 - s0 > share_jump_abs and s1 > s0 * share_jump_rel:
                findings.append({
                    "tier": cur["tier"], "kind": "attribution-shift",
                    "detail": (
                        f"{family} share {s0:.1%} -> {s1:.1%} "
                        f"(+{share_jump_abs:.0%} abs and "
                        f"{share_jump_rel:g}x rel exceeded)"
                    ),
                })
    cliff: Optional[int] = min(
        (f["tier"] for f in findings), default=None
    )
    return {"cliff_tier": cliff, "findings": findings}


# -- shared edge-trigger/dedupe helper ---------------------------------------

class EdgeTrigger:
    """Edge-triggered episode set shared by every sentinel: a key FIRES
    once when it first appears, stays silent while the episode persists,
    and re-arms once the episode ends (``settle`` with the keys seen this
    tick). PR 13 duplicated this pattern inline; one helper now owns it."""

    def __init__(self):
        self._active: set = set()

    def fire(self, key) -> bool:
        """True exactly when ``key`` newly activates (the edge)."""
        if key in self._active:
            return False
        self._active.add(key)
        return True

    def settle(self, seen) -> None:
        """End every episode whose key was NOT seen this tick — it
        re-arms and can fire again."""
        self._active &= set(seen)

    def active(self) -> set:
        return set(self._active)

    def clear(self) -> None:
        self._active.clear()


# -- the process-wide cumulative profile ------------------------------------

_CUM_LOCK = threading.Lock()
_CUMULATIVE = None


def cumulative_profile() -> dict:
    """The process's streaming span profile (installed once on the
    default tracer, like the metrics bridge). Sentinels diff this
    against their own cursors — N bundles share one on_finish hook."""
    global _CUMULATIVE
    with _CUM_LOCK:
        if _CUMULATIVE is None:
            from ..trace.export import SpanAggregator
            from ..trace.spans import TRACER

            _CUMULATIVE = SpanAggregator()
            TRACER.on_finish(_CUMULATIVE)
        return _CUMULATIVE.profile()


class SteadyStateSentinel:
    """Rolling per-tick attribution baseline + edge-triggered regression
    events. One per Obs bundle; ticked on the liveness cadence."""

    def __init__(self, clock=None, recorder=None, profile_source=None,
                 warmup_ticks: int = WARMUP_TICKS,
                 share_jump_abs: float = SHARE_JUMP_ABS,
                 share_jump_rel: float = SHARE_JUMP_REL,
                 tick_wall_ratio: float = TICK_WALL_RATIO,
                 tick_wall_floor_ms: float = TICK_WALL_FLOOR_MS,
                 family_floor_ms: float = FAMILY_FLOOR_MS):
        self.clock = clock
        self.recorder = recorder
        # deterministic harnesses flip this off: findings stay readable
        # (wall plane, /debug/sentinel) but never enter the event stream
        self.publish_events = True
        self._source = profile_source or cumulative_profile
        self.warmup_ticks = int(warmup_ticks)
        self.share_jump_abs = float(share_jump_abs)
        self.share_jump_rel = float(share_jump_rel)
        self.tick_wall_ratio = float(tick_wall_ratio)
        self.tick_wall_floor_ms = float(tick_wall_floor_ms)
        self.family_floor_ms = float(family_floor_ms)
        self._lock = threading.Lock()
        self._cursor: dict[str, float] = {}     # span name -> total_ms seen
        self._baseline: dict[str, float] = {}   # family -> EWMA share
        self._wall_ewma: Optional[float] = None
        self._wall_hist: deque = deque(maxlen=P99_WINDOW)
        self._ticks = 0
        self._edges = EdgeTrigger()             # (kind, family) episodes
        self._share_exported: set = set()       # families on the gauge
        self.findings: deque = deque(maxlen=FINDINGS_CAP)
        self.last_tick: dict = {}

    def _now(self) -> float:
        if self.clock is not None:
            return self.clock.now()
        import time

        return time.monotonic()

    # -- the tick ----------------------------------------------------------
    def tick(self, now: Optional[float] = None) -> list[dict]:
        """One judgment pass: diff the cumulative profile, update the
        baseline, raise edge-triggered findings. Returns the findings
        NEW this tick."""
        now = self._now() if now is None else now
        profile = self._source()
        spans = profile.get("spans", profile)  # tolerate bare span maps
        delta: dict[str, float] = {}
        jit_ms = 0.0
        with self._lock:
            for name, cell in spans.items():
                total = float(cell["total_ms"])
                d = total - self._cursor.get(name, 0.0)
                self._cursor[name] = total
                if name.startswith("sim."):
                    # driver container spans CONTAIN the controller spans
                    # (and exist only under the simulator) — folding them
                    # in would double-count every reconcile
                    continue
                if name.startswith("jit."):
                    # compile spans are nested INSIDE the dispatching
                    # span (solve.dispatch / consolidate.screen), so
                    # their wall is already attributed to the owner —
                    # folding them in double-counts every compile and
                    # invents a "jit" family; the retrace sentinel is
                    # the compile plane's judge, not this one. Their
                    # delta still gates the tick below (COMPILE_GRACE_MS)
                    if d > 0:
                        jit_ms += d
                    continue
                if d > 0:
                    family = span_family(name)
                    delta[family] = delta.get(family, 0.0) + d
            tick_ms = sum(delta.values())
            if jit_ms >= COMPILE_GRACE_MS:
                # compile-dominated tick: not steady state — no judgment
                # (the retrace sentinel owns the compile plane), no
                # episode re-arm, and the inflated wall stays out of the
                # baseline so the NEXT genuinely-steady tick is judged
                # against an honest floor
                self.last_tick = {
                    "at": round(now, 3),
                    "tick_wall_ms": round(tick_ms, 3),
                    "compile_grace_ms": round(jit_ms, 3),
                    "shares": {},
                }
                self._export_gauges(delta, tick_ms)
                return []
            new = self._judge_locked(delta, tick_ms, now)
            self._ticks += 1
            self._wall_hist.append(tick_ms)
            # baseline update AFTER judging: a regression tick must not
            # teach the baseline that regressed is normal before it is
            # flagged (it still folds in afterwards, so a persistent new
            # plateau stops alerting — edge-triggered, not a stuck page)
            if self._wall_ewma is None:
                self._wall_ewma = tick_ms
            else:
                self._wall_ewma += EWMA_ALPHA * (tick_ms - self._wall_ewma)
            if tick_ms >= SHARE_FLOOR_MS:
                for family, d in delta.items():
                    share = d / tick_ms
                    base = self._baseline.get(family)
                    self._baseline[family] = (
                        share if base is None
                        else base + EWMA_ALPHA * (share - base)
                    )
            self.last_tick = {
                "at": round(now, 3),
                "tick_wall_ms": round(tick_ms, 3),
                "shares": {
                    f: round(d / tick_ms, 4) for f, d in sorted(delta.items())
                } if tick_ms > 0 else {},
            }
        self._export_gauges(delta, tick_ms)
        for f in new:
            self._raise(f)
        return new

    def _judge_locked(self, delta: dict, tick_ms: float,
                      now: float) -> list[dict]:
        new: list[dict] = []
        if self._ticks < self.warmup_ticks:
            return new
        seen: set = set()
        # share jump: one subsystem suddenly dominates the tick
        if tick_ms >= SHARE_FLOOR_MS:
            for family, d in delta.items():
                if d < self.family_floor_ms:
                    continue  # sub-floor wall: burst texture, not a cliff
                share = d / tick_ms
                base = self._baseline.get(family, 0.0)
                if (share - base > self.share_jump_abs
                        and share > base * self.share_jump_rel):
                    key = ("attribution-shift", family)
                    seen.add(key)
                    if self._edges.fire(key):
                        new.append({
                            "at": round(now, 3),
                            "kind": "attribution-shift",
                            "family": family,
                            "detail": (
                                f"{family} share {base:.1%} -> {share:.1%} "
                                f"of a {tick_ms:.0f}ms tick "
                                f"(+{self.share_jump_abs:.0%} abs and "
                                f"{self.share_jump_rel:g}x rel exceeded)"
                            ),
                        })
        # tick blowup: the whole steady-state pass went super-linear
        # against its own rolling baseline; name the top-growing family
        base_wall = self._wall_ewma or 0.0
        if (base_wall > 0
                and tick_ms > base_wall * self.tick_wall_ratio
                and tick_ms - base_wall > self.tick_wall_floor_ms):
            top = max(delta, key=delta.get, default="?")
            key = ("tick-superlinear", top)
            seen.add(key)
            if self._edges.fire(key):
                new.append({
                    "at": round(now, 3),
                    "kind": "tick-superlinear",
                    "family": top,
                    "detail": (
                        f"tick wall {tick_ms:.0f}ms vs baseline "
                        f"{base_wall:.0f}ms (> {self.tick_wall_ratio:g}x); "
                        f"led by {top} ({delta.get(top, 0.0):.0f}ms)"
                    ),
                })
        # episodes that calmed down re-arm (edge-triggered)
        self._edges.settle(seen)
        self.findings.extend(new)
        return new

    def _raise(self, finding: dict) -> None:
        try:
            from ..metrics import SENTINEL_REGRESSIONS

            SENTINEL_REGRESSIONS.inc(
                family=finding["family"], kind=finding["kind"]
            )
        except Exception:
            pass
        if self.recorder is not None and self.publish_events:
            try:
                from ..events import WARNING

                self.recorder.publish(
                    "Sentinel", finding["family"], "SteadyStateRegression",
                    finding["detail"], type=WARNING,
                )
            except Exception:
                pass

    def _export_gauges(self, delta: dict, tick_ms: float) -> None:
        try:
            from ..metrics import SENTINEL_SHARE, SENTINEL_TICK_WALL
        except Exception:
            return
        SENTINEL_TICK_WALL.set(round(tick_ms, 3))
        exported: set = set()
        if tick_ms > 0:
            # bounded cardinality: only the tick's top families
            top = sorted(delta.items(), key=lambda kv: -kv[1])[:12]
            for family, d in top:
                SENTINEL_SHARE.set(round(d / tick_ms, 4), family=family)
                exported.add(family)
        # families absent from THIS tick drop to 0: the gauge documents
        # one tick's profile, and stale shares from earlier ticks would
        # sum past 1.0 and mislead attribution triage
        for family in self._share_exported - exported:
            SENTINEL_SHARE.set(0.0, family=family)
        self._share_exported = exported

    # -- introspection (/debug/sentinel) -----------------------------------
    def summary(self) -> dict:
        from .sli import percentile

        with self._lock:
            hist = list(self._wall_hist)
            return {
                "ticks": self._ticks,
                "warmed_up": self._ticks >= self.warmup_ticks,
                "baseline_shares": {
                    f: round(s, 4) for f, s in sorted(self._baseline.items())
                },
                "tick_wall_ewma_ms": (
                    round(self._wall_ewma, 3)
                    if self._wall_ewma is not None else None
                ),
                "tick_wall_p99_ms": percentile(hist, 0.99),
                "last_tick": dict(self.last_tick),
                "active_episodes": sorted(
                    f"{kind}:{family}" for kind, family in self._edges.active()
                ),
                "findings": [dict(f) for f in self.findings],
            }

    def reset(self) -> None:
        """Fresh baseline AND a fresh cursor over the cumulative profile:
        spans recorded before the reset (a previous run's, a fleet
        build's) must not land in the first tick's delta."""
        profile = self._source()
        spans = profile.get("spans", profile)
        with self._lock:
            self._cursor = {
                name: float(cell["total_ms"]) for name, cell in spans.items()
            }
            self._baseline.clear()
            self._wall_ewma = None
            self._wall_hist.clear()
            self._ticks = 0
            self._edges.clear()
            self.findings.clear()
            self.last_tick = {}


# -- the device-plane retrace sentinel ---------------------------------------

#: ticks before the retrace sentinel judges: legitimate compiles happen
#: while the process discovers its ladder buckets (the first wave of each
#: size, the first screen of each node bucket).
RETRACE_WARMUP_TICKS = 5
#: a family compiling on this many CONSECUTIVE ticks is a storm — one
#: compile is the ladder growing across a boundary (expected, absorbed),
#: repetition means shapes are flapping past the ladder every pass (the
#: ~270ms vmap-screen re-jit cliff's signature)
RETRACE_STORM_TICKS = 2
#: ...as is this many distinct new signatures inside ONE tick
RETRACE_STORM_BURST = 3


class RetraceSentinel:
    """Edge-triggered ``DeviceRetraceStorm`` findings off the jitwatch
    ledger (trace/jitwatch.py): the compile discipline says a warmed-up
    steady state retraces ~zero times — a single compile is the ladder
    absorbing growth across one boundary, but a family that keeps
    compiling (consecutive ticks, or a burst of signatures in one tick)
    has shapes flapping PAST the ladder, and the finding NAMES the
    program family and the signature axis that changed — the exact
    attribution the two prior compile cliffs (the vmap-screen re-jit,
    the cold lane solve) lacked.

    One per Obs bundle, ticked on the liveness cadence beside the
    steady-state sentinel. Deterministic harnesses set
    ``publish_events = False`` exactly like the steady-state sentinel:
    findings stay readable (``/debug/device``, the fleet report's wall
    plane) but never enter the signed event stream. The hard ZERO-compile
    contract lives in the gates, where the window is controlled:
    ``retraces_after_warmup`` (fleet gate) and ``steady_state_retraces``
    (bench gate)."""

    def __init__(self, clock=None, recorder=None,
                 warmup_ticks: int = RETRACE_WARMUP_TICKS,
                 storm_ticks: int = RETRACE_STORM_TICKS,
                 storm_burst: int = RETRACE_STORM_BURST):
        self.clock = clock
        self.recorder = recorder
        self.publish_events = True
        self.warmup_ticks = int(warmup_ticks)
        self.storm_ticks = int(storm_ticks)
        self.storm_burst = int(storm_burst)
        self._lock = threading.Lock()
        self._ticks = 0
        self._cursor = 0          # ledger seq already judged
        self._streak: dict[str, int] = {}  # family -> consecutive ticks
        self._edges = EdgeTrigger()
        self.findings: deque = deque(maxlen=FINDINGS_CAP)
        self.last_tick: dict = {}

    def _now(self) -> float:
        if self.clock is not None:
            return self.clock.now()
        import time

        return time.monotonic()

    def tick(self, now: Optional[float] = None) -> list[dict]:
        """One judgment pass: diff the ledger's compile seq against the
        cursor; after warmup, every new compile event is a storm edge.
        Also refreshes the device accountant's live-bytes gauge."""
        from ..trace import jitwatch

        if not jitwatch.enabled():
            return []
        now = self._now() if now is None else now
        led = jitwatch.ledger()
        new: list[dict] = []
        with self._lock:
            events = led.events_since(self._cursor)
            self._cursor = led.seq()
            self._ticks += 1
            warmed = self._ticks > self.warmup_ticks
            by_family: dict[str, list] = {}
            for ev in events:
                by_family.setdefault(ev["family"], []).append(ev)
            # consecutive-tick streaks: a family absent this tick re-arms
            for family in list(self._streak):
                if family not in by_family:
                    self._streak.pop(family)
            seen: set = set()
            for family, evs in by_family.items():
                streak = self._streak.get(family, 0) + 1
                self._streak[family] = streak
                stormy = (
                    streak >= self.storm_ticks
                    or len(evs) >= self.storm_burst
                )
                if not (warmed and stormy):
                    continue
                key = ("retrace-storm", family)
                seen.add(key)
                if self._edges.fire(key):
                    last = evs[-1]
                    wall = sum(e["wall_ms"] for e in evs)
                    new.append({
                        "at": round(now, 3),
                        "kind": "retrace-storm",
                        "family": family,
                        "changed": last["changed"],
                        "detail": (
                            f"{family} keeps compiling in steady state "
                            f"({len(evs)} new signatures this tick, "
                            f"{streak} consecutive ticks, {wall:.0f}ms): "
                            f"last change {last['changed']} — shapes are "
                            f"flapping past the ladder"
                        ),
                    })
            self._edges.settle(seen)
            self.findings.extend(new)
            self.last_tick = {
                "at": round(now, 3),
                "compiles": len(events),
                "warmed_up": warmed,
            }
        # live-bytes gauge + HBM watermark ride the sentinel cadence
        try:
            from .device import DeviceAccountant

            DeviceAccountant().export()
        except Exception:
            pass
        for f in new:
            self._raise(f)
        return new

    def _raise(self, finding: dict) -> None:
        if self.recorder is not None and self.publish_events:
            try:
                from ..events import WARNING

                self.recorder.publish(
                    "Sentinel", finding["family"], "DeviceRetraceStorm",
                    finding["detail"], type=WARNING,
                )
            except Exception:
                pass

    def summary(self) -> dict:
        with self._lock:
            return {
                "ticks": self._ticks,
                "warmed_up": self._ticks > self.warmup_ticks,
                "cursor": self._cursor,
                "active_episodes": sorted(
                    f"{kind}:{family}" for kind, family in self._edges.active()
                ),
                "last_tick": dict(self.last_tick),
                "findings": [dict(f) for f in self.findings],
            }

    def reset(self) -> None:
        """Fresh warmup AND a fresh cursor: compiles recorded before the
        reset (a previous run's, a fleet build's) are not this run's
        storms."""
        from ..trace import jitwatch

        with self._lock:
            self._cursor = jitwatch.ledger().seq()
            self._ticks = 0
            self._streak.clear()
            self._edges.clear()
            self.findings.clear()
            self.last_tick = {}
