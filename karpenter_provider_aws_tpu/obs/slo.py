"""SLO engine: declarative objectives + multi-window burn-rate alerting.

An ``SLOSpec`` declares what the control plane promises (pod time-to-bind,
nodeclaim time-to-ready, ...) as a target ratio over a compliance window
plus Google-SRE-style multi-window burn rules: a rule fires only when the
error-budget burn rate exceeds its factor over BOTH the long and the short
window — fast enough to page on a real regression, immune to a single bad
minute.

The engine is fed discrete SLI events (good/bad, clock-stamped) by the
lifecycle observer and controllers, evaluates inside the liveness loop
(``Obs.tick``), exports ``karpenter_slo_error_budget_remaining{slo}`` /
``karpenter_slo_burn_rate{slo,window}`` gauges, and publishes a Warning
event per newly-firing fast burn. All time comes from the injected clock,
so chaos scenarios exercise burn alerts deterministically.

Spec format (JSON-ready, ``SLOSpec.from_dict``)::

    {"name": "pod-time-to-bind", "objective": 0.99, "window_s": 3600,
     "threshold_s": 300, "description": "...",
     "burn_rules": [{"long_s": 3600, "short_s": 300, "factor": 14.4},
                    {"long_s": 21600, "short_s": 1800, "factor": 6.0}]}
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

# Default burn rules: the classic 2%-of-budget-in-1h page and the
# 5%-in-6h ticket (SRE workbook chapter 5), scaled to our windows.
DEFAULT_BURN_RULES = ((3600.0, 300.0, 14.4), (21600.0, 1800.0, 6.0))

EVENTS_PER_SLO = 8192  # bounded per-SLO event history


@dataclass(frozen=True)
class BurnRule:
    long_s: float
    short_s: float
    factor: float

    def as_dict(self) -> dict:
        return {"long_s": self.long_s, "short_s": self.short_s, "factor": self.factor}


@dataclass
class SLOSpec:
    """One declared objective. ``threshold_s`` classifies latency samples
    (good iff <= threshold); ratio-style SLIs skip it and record
    good/bad directly."""

    name: str
    objective: float = 0.99            # target good-ratio
    window_s: float = 3600.0           # compliance window for the budget gauge
    threshold_s: Optional[float] = None
    description: str = ""
    burn_rules: tuple = tuple(BurnRule(*r) for r in DEFAULT_BURN_RULES)

    @property
    def budget(self) -> float:
        """Allowed error ratio (never 0: a 1.0 objective would make any
        single bad event an infinite burn)."""
        return max(1.0 - self.objective, 1e-9)

    def as_dict(self) -> dict:
        return {
            "name": self.name,
            "objective": self.objective,
            "window_s": self.window_s,
            "threshold_s": self.threshold_s,
            "description": self.description,
            "burn_rules": [r.as_dict() for r in self.burn_rules],
        }

    @staticmethod
    def from_dict(d: dict) -> "SLOSpec":
        rules = tuple(
            BurnRule(float(r["long_s"]), float(r["short_s"]), float(r["factor"]))
            for r in d.get("burn_rules", [])
        ) or tuple(BurnRule(*r) for r in DEFAULT_BURN_RULES)
        return SLOSpec(
            name=str(d["name"]),
            objective=float(d.get("objective", 0.99)),
            window_s=float(d.get("window_s", 3600.0)),
            threshold_s=(
                float(d["threshold_s"]) if d.get("threshold_s") is not None else None
            ),
            description=str(d.get("description", "")),
            burn_rules=rules,
        )


def default_slos() -> list[SLOSpec]:
    """The control plane's shipped promises (docs/observability.md)."""
    return [
        SLOSpec(
            name="pod-time-to-bind",
            objective=0.99,
            window_s=3600.0,
            threshold_s=300.0,
            description="99% of pods bind within 5 minutes of going pending",
        ),
        SLOSpec(
            name="nodeclaim-time-to-ready",
            objective=0.99,
            window_s=3600.0,
            threshold_s=900.0,
            description="99% of nodeclaims are initialized within 15 minutes "
                        "of creation (liveness reaps count as misses)",
        ),
        SLOSpec(
            name="solve-success",
            objective=0.999,
            window_s=3600.0,
            description="99.9% of solve passes place every pod they were "
                        "handed (a pass leaving pods unschedulable is a miss)",
        ),
    ]


class SLOEngine:
    """Event store + evaluator. Thread-safe; all timestamps come from the
    injected clock (or the event producers' own stamps)."""

    def __init__(self, clock=None, recorder=None, specs=None):
        self.clock = clock
        self.recorder = recorder
        self._lock = threading.Lock()
        self._specs: dict[str, SLOSpec] = {}
        self._events: dict[str, deque] = {}   # slo -> deque[(t, good)]
        self._firing: set[tuple[str, float]] = set()  # (slo, long_s) active burns
        for spec in (specs if specs is not None else default_slos()):
            self.configure(spec)

    def _now(self) -> float:
        if self.clock is not None:
            return self.clock.now()
        import time

        return time.monotonic()

    # -- spec management ---------------------------------------------------
    def configure(self, spec: SLOSpec) -> SLOSpec:
        """Install or replace one SLO spec (history is kept — re-declaring
        a target mid-flight re-judges the same events)."""
        with self._lock:
            self._specs[spec.name] = spec
            self._events.setdefault(spec.name, deque(maxlen=EVENTS_PER_SLO))
        return spec

    def spec(self, name: str) -> Optional[SLOSpec]:
        with self._lock:
            return self._specs.get(name)

    def specs(self) -> list[SLOSpec]:
        with self._lock:
            return list(self._specs.values())

    # -- SLI feed ----------------------------------------------------------
    def record(self, slo: str, good: bool, at: Optional[float] = None) -> None:
        at = self._now() if at is None else at
        with self._lock:
            q = self._events.get(slo)
            if q is None:  # undeclared SLO: auto-register with defaults
                self._specs[slo] = SLOSpec(name=slo)
                q = self._events[slo] = deque(maxlen=EVENTS_PER_SLO)
            q.append((at, bool(good)))

    def record_latency(self, slo: str, seconds: float, at: Optional[float] = None) -> None:
        """Judge one latency sample against the spec's threshold (specs
        without a threshold treat every sample as good)."""
        spec = self.spec(slo)
        thr = spec.threshold_s if spec is not None else None
        self.record(slo, thr is None or seconds <= thr, at=at)

    def record_bad(self, slo: str, at: Optional[float] = None) -> None:
        self.record(slo, False, at=at)

    # -- evaluation --------------------------------------------------------
    def _ratio(self, events, t0: float, now: float) -> tuple[int, int]:
        """(bad, total) within (t0, now]."""
        bad = total = 0
        for t, good in events:
            if t0 < t <= now:
                total += 1
                if not good:
                    bad += 1
        return bad, total

    @staticmethod
    def _windower(events):
        """Build an O(log n) window counter over one spec's events.

        Returns ``window(t0, now) -> (bad, total)`` equivalent to
        :meth:`_ratio`. Events almost always arrive in time order (they
        are stamped by a monotonic clock), so a prefix-bad-count array +
        two bisects answers each window query without rescanning the
        whole deque — the difference between O(events) and O(log events)
        per window matters once a fleet-simulator day has pushed the
        per-SLO history to its 8192 cap and every liveness tick evaluates
        five windows per spec. Falls back to the exact linear scan when
        the history is out of order."""
        import bisect

        times = [t for t, _ in events]
        for i in range(1, len(times)):
            if times[i] < times[i - 1]:
                return None  # unsorted: caller uses the linear scan
        bad_prefix = [0]
        for _, good in events:
            bad_prefix.append(bad_prefix[-1] + (0 if good else 1))

        def window(t0: float, now: float) -> tuple[int, int]:
            hi = bisect.bisect_right(times, now)
            lo = bisect.bisect_right(times, t0)
            return bad_prefix[hi] - bad_prefix[lo], hi - lo

        return window

    def evaluate(self, now: Optional[float] = None) -> dict:
        """One evaluation pass: refresh gauges, fire/clear burn alerts.
        Returns the JSON-ready snapshot /debug/slo serves."""
        from ..metrics import SLO_BUDGET_REMAINING, SLO_BURN_RATE

        now = self._now() if now is None else now
        with self._lock:
            work = [
                (spec, list(self._events.get(spec.name, ())))
                for spec in self._specs.values()
            ]
        out: dict = {"at": round(now, 3), "slos": []}
        for spec, events in work:
            win = self._windower(events)
            if win is None:
                win = lambda t0, t1: self._ratio(events, t0, t1)  # noqa: E731
            bad, total = win(now - spec.window_s, now)
            err = bad / total if total else 0.0
            remaining = max(0.0, 1.0 - err / spec.budget)
            SLO_BUDGET_REMAINING.set(remaining, slo=spec.name)
            rules_out = []
            for rule in spec.burn_rules:
                bad_l, tot_l = win(now - rule.long_s, now)
                bad_s, tot_s = win(now - rule.short_s, now)
                burn_l = (bad_l / tot_l / spec.budget) if tot_l else 0.0
                burn_s = (bad_s / tot_s / spec.budget) if tot_s else 0.0
                SLO_BURN_RATE.set(
                    burn_l, slo=spec.name, window=f"{int(rule.long_s)}s"
                )
                firing = burn_l >= rule.factor and burn_s >= rule.factor
                key = (spec.name, rule.long_s)
                with self._lock:
                    was = key in self._firing
                    if firing:
                        self._firing.add(key)
                    else:
                        self._firing.discard(key)
                if firing and not was and self.recorder is not None:
                    from ..events import WARNING

                    self.recorder.publish(
                        "SLO", spec.name, "SLOFastBurn",
                        f"error budget burning {burn_l:.1f}x sustainable "
                        f"over {int(rule.long_s)}s (threshold {rule.factor}x; "
                        f"{bad_l}/{tot_l} bad)",
                        type=WARNING,
                    )
                rules_out.append({
                    "long_s": rule.long_s, "short_s": rule.short_s,
                    "factor": rule.factor,
                    "burn_long": round(burn_l, 3),
                    "burn_short": round(burn_s, 3),
                    "firing": firing,
                })
            out["slos"].append({
                "name": spec.name,
                "objective": spec.objective,
                "window_s": spec.window_s,
                "threshold_s": spec.threshold_s,
                "events_in_window": total,
                "bad_in_window": bad,
                "error_ratio": round(err, 5),
                "budget_remaining": round(remaining, 4),
                "burn_rules": rules_out,
            })
        return out

    def reset(self) -> None:
        with self._lock:
            for q in self._events.values():
                q.clear()
            self._firing.clear()
