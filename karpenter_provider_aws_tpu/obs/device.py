"""The device-plane accountant: what the chip compiles, holds, and ships.

``trace/jitwatch.py`` records *compiles* (the ledger);
``ops/device_state.py`` records *residency outcomes and bytes* (the
holder LRU + ``karpenter_device_state_bytes_total``); the solver and
sidecar record *upload payloads*. This module folds all three into one
judgment surface:

- :class:`DeviceAccountant` — per-family live-buffer estimate (last
  dispatch's abstract input bytes; the device-state mirrors' ACTUAL
  buffer bytes), cumulative link bytes, and an HBM-watermark estimate
  (the max total live estimate this process has seen). Exported on
  ``karpenter_device_live_bytes{family}``.
- ``/debug/device`` — the full observatory page: ledger snapshot
  (compile/retrace/hit counts, attribution, first-compile callsites),
  residency map, link accounting, watermark, and the retrace sentinel's
  findings. Registered by ``obs.install()``.
- ``obs device`` CLI rendering — ledger table + top retracers +
  residency map, from the live process or a ``--snapshot-file`` (a saved
  ``/debug/device`` page or ``sim run``'s device plane), so a collected
  artifact round-trips offline (the ``make device-obs-smoke`` contract).

Estimates are labeled estimates: the live-bytes gauge is derived from
abstract input shapes (what a dispatch *presents* to the device), not a
runtime allocator dump — good enough to rank families and catch a
residency leak, not a byte-exact HBM profiler.
"""

from __future__ import annotations

import threading
from typing import Optional

from ..trace import jitwatch

# process-wide HBM watermark estimate (monotonic; reset with the ledger)
_WM_LOCK = threading.Lock()
_WATERMARK = {"bytes": 0}


class DeviceAccountant:
    """Folds the jitwatch ledger + device_state holders + byte counters
    into the device plane's summary. Stateless apart from the module
    watermark — build one wherever needed."""

    def residency_map(self) -> list[dict]:
        """The device-state mirror LRU: one row per live holder with its
        actual buffer bytes (the scatter-patched screen tensors)."""
        rows: list[dict] = []
        try:
            from ..ops import device_state as ds

            with ds._HOLDERS_LOCK:
                holders = list(ds._HOLDERS.values())
            for h in holders:
                bufs = h.arrays()
                nbytes = 0
                if bufs is not None:
                    for b in bufs[:5]:
                        nbytes += int(getattr(b, "nbytes", 0) or 0)
                rows.append({
                    "nodes_live": h.n_live,
                    "node_bucket": h.NB,
                    "group_bucket": h.GB,
                    "slot_width": h.S,
                    "resident_bytes": nbytes,
                    "usable": bufs is not None,
                })
        except Exception:
            pass
        return rows

    def link_bytes(self) -> dict:
        """Cumulative host->device link accounting, by source."""
        out: dict = {}
        try:
            from ..metrics import DEVICE_STATE_BYTES

            out["device_state.upload"] = DEVICE_STATE_BYTES.value(kind="upload")
            out["device_state.patch"] = DEVICE_STATE_BYTES.value(kind="patch")
        except Exception:
            pass
        out.update(jitwatch.ledger().dispatch_bytes())
        return out

    def live_bytes(self, residency: Optional[list] = None) -> dict:
        """Per-family live-buffer estimate: each program family's last
        dispatch footprint, plus the mirrors' actual resident bytes.
        Pass a precomputed ``residency_map()`` to avoid re-walking the
        holder LRU."""
        out = dict(jitwatch.ledger().live_arg_bytes())
        rows = self.residency_map() if residency is None else residency
        mirror = sum(r["resident_bytes"] for r in rows)
        if mirror:
            out["device_state.mirror"] = mirror
        return out

    def export(self, live: Optional[dict] = None) -> int:
        """Publish the live-bytes gauge per family and advance the HBM
        watermark; returns the current total estimate. Cheap by design —
        the retrace sentinel calls this every liveness tick (no event
        ring is copied; see ``JitLedger.live_arg_bytes``)."""
        live = self.live_bytes() if live is None else live
        total = int(sum(live.values()))
        try:
            from ..metrics import DEVICE_LIVE_BYTES

            for family, n in live.items():
                DEVICE_LIVE_BYTES.set(float(n), family=family)
        except Exception:
            pass
        with _WM_LOCK:
            if total > _WATERMARK["bytes"]:
                _WATERMARK["bytes"] = total
        return total

    def summary(self) -> dict:
        """The ``/debug/device`` payload (JSON-ready, self-contained —
        the ``obs device`` CLI renders exactly this snapshot). The
        ledger snapshot and residency walk are taken ONCE and reused."""
        residency = self.residency_map()
        live = self.live_bytes(residency=residency)
        total = self.export(live=live)
        with _WM_LOCK:
            watermark = _WATERMARK["bytes"]
        return {
            "jitwatch": jitwatch.ledger().snapshot(),
            "top_retracers": jitwatch.ledger().top_retracers(),
            "residency": residency,
            "link_bytes": self.link_bytes(),
            "live_bytes": live,
            "live_bytes_total": total,
            "hbm_watermark_bytes": watermark,
        }


def reset_watermark() -> None:
    with _WM_LOCK:
        _WATERMARK["bytes"] = 0


def device_summary(retrace_sentinel=None) -> dict:
    """Build the full observatory page; with a sentinel attached, its
    findings ride along (what ``/debug/device`` serves)."""
    out = DeviceAccountant().summary()
    if retrace_sentinel is not None:
        try:
            out["retrace_sentinel"] = retrace_sentinel.summary()
        except Exception:
            pass
    return out


# ---------------------------------------------------------------------------
# CLI rendering (obs/__main__.py `device` subcommand)
# ---------------------------------------------------------------------------

def _fmt_bytes(n) -> str:
    n = float(n or 0)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}GiB"  # pragma: no cover - loop always returns


def render_device(snapshot: dict) -> str:
    """Human rendering of a device-observatory snapshot (the summary()
    dict, a saved /debug/device page, or a sim report's device plane)."""
    jw = snapshot.get("jitwatch", snapshot)
    lines: list[str] = []
    families = jw.get("families", {})
    traces = jw.get("seq") or sum(
        f.get("compiles", 0) + f.get("retraces", 0)
        for f in families.values()
    )
    lines.append(
        f"jitwatch ledger: {'armed' if jw.get('enabled', True) else 'OFF'}, "
        f"{len(families)} program families, "
        f"{traces} total (re)traces"
    )
    if families:
        header = (
            f"  {'family':<26} {'compiles':>8} {'retraces':>8} {'hits':>8} "
            f"{'compile_ms':>10} {'last_change'}"
        )
        lines.append(header)
        for name, fam in sorted(families.items()):
            lines.append(
                f"  {name:<26} {fam['compiles']:>8} {fam['retraces']:>8} "
                f"{fam['hits']:>8} {fam['compile_ms_total']:>10.1f} "
                f"{fam.get('last_change', '')}"
            )
    top = snapshot.get("top_retracers") or []
    retracers = [f for f in top if f.get("retraces")]
    if retracers:
        lines.append("top retracers:")
        for fam in retracers:
            lines.append(
                f"  {fam['family']}: {fam['retraces']} retraces "
                f"(last: {fam.get('last_change', '?')}; "
                f"callsite {fam.get('callsite', '?')})"
            )
    res = snapshot.get("residency") or []
    if res:
        lines.append("residency map (device-state mirrors):")
        for r in res:
            lines.append(
                f"  nodes={r['nodes_live']}/{r['node_bucket']} "
                f"groups<={r['group_bucket']} slots={r['slot_width']} "
                f"{_fmt_bytes(r['resident_bytes'])}"
                f"{'' if r['usable'] else ' (UNUSABLE)'}"
            )
    link = snapshot.get("link_bytes") or {}
    if link:
        lines.append("cumulative link bytes: " + ", ".join(
            f"{k}={_fmt_bytes(v)}" for k, v in sorted(link.items())
        ))
    if "live_bytes_total" in snapshot:
        lines.append(
            f"live-bytes estimate: {_fmt_bytes(snapshot['live_bytes_total'])} "
            f"(HBM watermark {_fmt_bytes(snapshot.get('hbm_watermark_bytes'))})"
        )
    mon = jw.get("monitoring") or {}
    if mon:
        lines.append("jax.monitoring compile events:")
        for k, cell in sorted(mon.items()):
            lines.append(
                f"  {k}: {cell['count']}x, {cell['total_s']:.2f}s"
            )
    sent = snapshot.get("retrace_sentinel")
    if sent:
        lines.append(
            f"retrace sentinel: {sent.get('ticks', 0)} ticks, "
            f"{len(sent.get('findings', []))} findings"
        )
        for f in sent.get("findings", []):
            lines.append(f"  [STORM] {f.get('detail')}")
    return "\n".join(lines)


def load_snapshot(path: str) -> dict:
    """Read a saved device snapshot: a /debug/device page, a summary()
    dump, or a fleet report (its ``wall.device`` plane is extracted)."""
    import json

    with open(path) as f:
        doc = json.load(f)
    if "wall" in doc and isinstance(doc.get("wall"), dict) \
            and "device" in doc["wall"]:
        return doc["wall"]["device"]
    return doc
