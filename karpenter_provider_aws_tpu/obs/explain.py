"""``obs explain``: join audit records, events, and trace provenance.

One question — "why did the controller decide X about <kind>/<name>?" —
answered from three planes at once: the decision audit ring (what was
chosen and what was rejected), the event recorder (what was announced),
and trace provenance (what machinery computed it). Works against live
in-process objects (hermetic tests, the operator) or a dumped audit JSONL
(the CLI's offline mode).
"""

from __future__ import annotations

from typing import Optional


def explain(
    kind: str,
    name: str,
    audit=None,
    recorder=None,
    limit: int = 50,
    slo: Optional[dict] = None,
) -> dict:
    """JSON-ready joined view for one object.

    ``kind`` is the subject kind (Pod / NodeClaim / Node / SLO ...);
    ``audit`` an AuditLog (or a pre-loaded list of AuditRecords);
    ``recorder`` an EventRecorder — or a list of event DICTS (the fleet
    report's ``events`` section), filtered here on kind/name. ``slo``
    attaches run-level SLO context (the fleet report's ``slo_summary``)
    to the view so a simulated day's decision reads with the day's
    promises beside it. Absent planes join as empty lists.
    """
    from types import SimpleNamespace

    records: list = []
    if audit is not None:
        if hasattr(audit, "query"):
            records = audit.query(subject_kind=kind, subject=name, limit=limit)
        else:  # a list loaded from JSONL
            records = [
                r for r in audit
                if r.subject_kind == kind and r.subject == name
            ][-limit:]
    events: list = []
    if recorder is not None:
        if hasattr(recorder, "query"):
            events = recorder.query(kind=kind, name=name)
        else:  # fleet-report event dicts
            events = [
                SimpleNamespace(
                    type=e.get("type", ""), reason=e.get("reason", ""),
                    message=e.get("message", ""), at=float(e.get("at", 0.0)),
                    count=int(e.get("count", 1)),
                )
                for e in recorder
                if e.get("kind") == kind and e.get("name") == name
            ]

    # provenance join: prefer the stamp each audit record carried at
    # decision time; fall back to the most recent live solve record
    provenance: Optional[dict] = None
    for r in reversed(records):
        stamp = r.detail.get("provenance")
        if stamp:
            provenance = stamp if isinstance(stamp, dict) else {"label": stamp}
            break
    if provenance is None:
        try:
            from ..trace.provenance import last_record

            rec = last_record("solve")
            if rec is not None:
                provenance = rec.as_dict()
        except Exception:
            provenance = None

    view = {
        "subject": f"{kind}/{name}",
        "audit": [r.as_dict() for r in records],
        "events": [
            {
                "type": e.type, "reason": e.reason, "message": e.message,
                "at": round(e.at, 3), "count": e.count,
            }
            for e in events
        ],
        "provenance": provenance,
    }
    if slo:
        view["slo"] = slo
    return view


def render_text(view: dict) -> str:
    """Human rendering of an ``explain`` view."""
    lines = [f"== {view['subject']} =="]
    if not view["audit"] and not view["events"]:
        lines.append("no audit records or events retained for this object")
    if view["audit"]:
        lines.append("decisions (oldest first):")
        for r in view["audit"]:
            detail = {
                k: v for k, v in r.get("detail", {}).items()
                if k != "provenance"
            }
            extra = f"  {detail}" if detail else ""
            lines.append(
                f"  [{r['at']:>10.3f}] {r['kind']:<13} {r['decision']}{extra}"
            )
    if view["events"]:
        lines.append("events:")
        for e in view["events"]:
            count = f" x{e['count']}" if e.get("count", 1) > 1 else ""
            lines.append(
                f"  [{e['at']:>10.3f}] {e['type']}/{e['reason']}{count}: "
                f"{e['message']}"
            )
    prov = view.get("provenance")
    if prov:
        if "label" in prov and len(prov) == 1:
            lines.append(f"provenance: {prov['label']}")
        else:
            lines.append(
                "provenance: "
                f"{prov.get('device', '?')}/{prov.get('backend', '?')}"
                f"@{prov.get('git_sha', '?')}"
                + (f" quality={prov['quality']}" if prov.get("quality") else "")
            )
    slo = view.get("slo")
    if slo:
        lines.append("run SLO context:")
        for name, d in sorted(slo.items()):
            lines.append(
                f"  {name}: budget_remaining>={d.get('min_budget_remaining')} "
                f"worst_burn={d.get('worst_burn')}"
            )
    return "\n".join(lines)
