"""FleetRecorder: the cross-replica flight recorder's merge + query side.

``trace/correlate.py`` threads one CorrelationId through every hop of a
pod/claim lifecycle, whichever replica performs it. This module turns
the recorded hops into answers:

- :meth:`FleetRecorder.explain` — the merged decision timeline for one
  object: its own hops PLUS the hops of every claim its chain links to
  (launch fences, cross-replica registration, adoption), ordered by the
  merge rule (store clock, then ledger seq — causal within one
  shared-world ledger — then fencing-token epoch for concatenated
  per-process snapshots; see :func:`~..trace.correlate.merge_key`),
  joined with the audit ring and events. ``python -m ...obs fleet
  explain pod/<name>`` renders it.
- :meth:`FleetRecorder.ownership_gantt` — who held which partition when:
  segments built from the ReplicaSet's edge-triggered ownership
  timeline, annotated with handoffs, adoptions, steals, and fenced-write
  rejections. ``obs fleet timeline`` renders it.
- :meth:`FleetRecorder.coverage` — the correlation-coverage gate metric:
  the fraction of bound pods whose chain is complete (carries every
  :data:`~..trace.correlate.REQUIRED_POD_HOPS` hop). ``make
  fleet-obs-smoke`` fails below 99%.

Sources, in order of preference:

- **live** — an ``Environment`` / ``ReplicaSetEnv`` (the testenv seam):
  the shared world's ledger, audit ring, event recorder, and — for
  replica sets — each elector's adoption/rebalance logs and the lease
  audit's ownership timeline.
- **serialized** — a flight snapshot (:meth:`snapshot` /
  :meth:`from_snapshot`): what real deployments serve per process at
  ``/debug/flight`` and what ``sim run --flight-out`` writes. Merging N
  processes' snapshots is concatenating their hop lists — correlation
  ids are pure functions of object identity, so the chains interleave
  with no translation.
"""

from __future__ import annotations

from typing import Optional

from ..trace.correlate import (
    CorrelationLedger,
    Hop,
    chain_complete,
    merge_key,
)

SNAPSHOT_SCHEMA = 1
RECORDS_CAP = 4096


class FleetRecorder:
    def __init__(self, env=None, ledger: Optional[CorrelationLedger] = None,
                 audit=None, events=None, ownership_timeline=None,
                 adoptions=None, rebalances=None, fenced_rejections=None,
                 bound_uids=None):
        self.env = env
        obs = getattr(env, "obs", None)
        self.ledger = ledger or (getattr(obs, "ledger", None)
                                 if obs is not None else None) \
            or CorrelationLedger()
        self.audit = audit if audit is not None else (
            getattr(obs, "audit", None) if obs is not None else None
        )
        self.events = events if events is not None else getattr(
            env, "events", None
        )
        self.ownership_timeline = list(
            ownership_timeline
            if ownership_timeline is not None
            else getattr(env, "ownership_timeline", ())
        )
        self._adoptions = adoptions
        self._rebalances = rebalances
        self._fenced = fenced_rejections
        self._bound_uids = bound_uids

    # -- collection --------------------------------------------------------
    def adoptions(self) -> list:
        if self._adoptions is not None:
            return list(self._adoptions)
        out = []
        for r in getattr(self.env, "replicas", ()):
            for key, claims in r.elector.adoptions:
                out.append({
                    "replica": r.identity, "partition": list(key),
                    "claims": list(claims),
                })
        return out

    def rebalances(self) -> list:
        if self._rebalances is not None:
            return list(self._rebalances)
        out = []
        for r in getattr(self.env, "replicas", ()):
            for reason, key in r.elector.rebalances:
                out.append({
                    "replica": r.identity, "reason": reason,
                    "partition": list(key),
                })
        return out

    def fenced_rejections(self) -> list:
        if self._fenced is not None:
            return list(self._fenced)
        cloud = getattr(self.env, "cloud", None)
        if cloud is None or not hasattr(cloud, "fenced_rejections"):
            return []
        with cloud._lock:
            return [
                {"lease": name, "token": tok, "current": cur, "api": api}
                for name, tok, cur, api in cloud.fenced_rejections
            ]

    def bound_uids(self) -> list[str]:
        if self._bound_uids is not None:
            return list(self._bound_uids)
        obs = getattr(self.env, "obs", None)
        sli = getattr(obs, "sli", None) if obs is not None else None
        return sli.bound_uids() if sli is not None else []

    # -- coverage (the fleet-obs-smoke gate) -------------------------------
    def coverage(self) -> dict:
        """Correlation coverage over bound pods: a chain is COMPLETE when
        it carries a lifecycle start (pending, or evict for drained pods
        re-entering) and the terminal bind. The denominator is the SLI's
        bind ring (bounded at 4096 — the smoke gate's scale sits well
        inside it)."""
        from ..trace.correlate import correlation_id

        uids = self.bound_uids()
        complete = 0
        for uid in uids:
            kinds = {h.kind for h in self.ledger.hops(
                correlation_id("Pod", uid)
            )}
            if chain_complete(kinds):
                complete += 1
        by_kind: dict[str, int] = {}
        for hop in self.ledger.all_hops():
            by_kind[hop.kind] = by_kind.get(hop.kind, 0) + 1
        return {
            "bound": len(uids),
            "complete": complete,
            "coverage": round(complete / len(uids), 4) if uids else None,
            "hops_total": len(self.ledger),
            "hops_by_kind": dict(sorted(by_kind.items())),
        }

    # -- the merged decision timeline --------------------------------------
    def timeline(self, cid: str) -> list[Hop]:
        return self.ledger.hops(cid)

    def explain(self, kind: str, name: str, limit: int = 200) -> dict:
        """The full cross-replica lifecycle of one object: its hops plus
        every linked claim's hops, merge-ordered, with the audit/event
        join beside them."""
        cid = self.ledger.resolve(kind, name)
        hops = list(self.ledger.hops(cid)) if cid else []
        # follow pod -> claim links (launch/nominate hops name the claim)
        linked: list[Hop] = []
        seen_claims: set = set()
        for hop in hops:
            claim = hop.detail.get("claim")
            if claim and claim not in seen_claims:
                seen_claims.add(claim)
                ccid = self.ledger.resolve("NodeClaim", claim)
                if ccid:
                    linked.extend(self.ledger.hops(ccid))
        merged = sorted(hops + linked, key=merge_key)[-limit:]
        view = {
            "subject": f"{kind}/{name}",
            "cid": cid,
            "hops": [h.as_dict() for h in merged],
            "replicas": sorted({h.replica for h in merged}),
            "linked_claims": sorted(seen_claims),
        }
        # audit/event join (the PR 4 explain planes, when sources exist)
        if self.audit is not None or self.events is not None:
            from .explain import explain as _explain

            base = _explain(kind, name, audit=self.audit,
                            recorder=self.events, limit=50)
            view["audit"] = base["audit"]
            view["events"] = base["events"]
        return view

    def render_explain(self, view: dict) -> str:
        lines = [f"== {view['subject']} "
                 f"(cid {view.get('cid') or 'unknown'}) =="]
        hops = view.get("hops", [])
        if not hops:
            lines.append("no correlated hops retained for this object")
        else:
            lines.append(
                f"lifecycle across {len(view.get('replicas', []))} "
                f"replica(s): {', '.join(view.get('replicas', []))}"
            )
            for h in hops:
                fence = ""
                if h.get("fence"):
                    fence = f" fence={h['fence'][0]}@{h['fence'][1]}"
                detail = h.get("detail") or {}
                extra = " ".join(
                    f"{k}={v}" for k, v in sorted(detail.items())
                )
                lines.append(
                    f"  [{h['at']:>10.3f}] {h['replica']:<12} "
                    f"{h['subject_kind']}/{h['subject']} {h['kind']}"
                    + (f"  {extra}" if extra else "") + fence
                )
        for rec in view.get("audit", [])[-10:]:
            lines.append(
                f"  audit [{rec['at']:>10.3f}] {rec['kind']}: "
                f"{rec['decision']}"
            )
        for ev in view.get("events", [])[-10:]:
            lines.append(
                f"  event [{ev['at']:>10.3f}] {ev['type']}/{ev['reason']}: "
                f"{ev['message']}"
            )
        return "\n".join(lines)

    # -- ownership Gantt ---------------------------------------------------
    def ownership_gantt(self, until: Optional[float] = None) -> dict:
        """Per-partition ownership segments from the edge-triggered
        timeline: who held which partition when, plus the handoff /
        adoption / steal / fence-rejection annotations."""
        segments: dict[str, list] = {}
        open_seg: dict[str, dict] = {}
        last_t = 0.0
        for t, key, _prev, cur, token in self.ownership_timeline:
            kname = "/".join(str(k) for k in key)
            last_t = max(last_t, t)
            seg = open_seg.pop(kname, None)
            if seg is not None:
                seg["to_s"] = t
            if cur:
                seg = {
                    "holder": cur, "from_s": t, "to_s": None, "token": token,
                }
                open_seg[kname] = seg
                segments.setdefault(kname, []).append(seg)
            else:
                segments.setdefault(kname, []).append({
                    "holder": "", "from_s": t, "to_s": None, "token": token,
                })
                open_seg[kname] = segments[kname][-1]
        horizon = until if until is not None else last_t
        for seg in open_seg.values():
            seg["to_s"] = None if horizon <= seg["from_s"] else horizon
        return {
            "segments": {k: v for k, v in sorted(segments.items())},
            "rebalances": self.rebalances(),
            "adoptions": self.adoptions(),
            "fenced_rejections": self.fenced_rejections(),
        }

    def render_gantt(self, gantt: Optional[dict] = None) -> str:
        g = gantt or self.ownership_gantt()
        lines = ["== partition ownership timeline =="]
        if not g["segments"]:
            lines.append("no ownership transitions recorded "
                         "(single replica or no lease audit)")
        for kname, segs in g["segments"].items():
            lines.append(f"{kname}:")
            for seg in segs:
                to = f"{seg['to_s']:.0f}s" if seg["to_s"] is not None else "…"
                holder = seg["holder"] or "(unowned)"
                lines.append(
                    f"  {seg['from_s']:>8.0f}s -> {to:<8} {holder}"
                    + (f"  token={seg['token']}" if seg["holder"] else "")
                )
        ad = g.get("adoptions", [])
        if ad:
            lines.append("adoptions:")
            for a in ad:
                if a["claims"]:
                    lines.append(
                        f"  {a['replica']} adopted "
                        f"{'/'.join(str(k) for k in a['partition'])}: "
                        f"{', '.join(a['claims'][:6])}"
                    )
        fr = g.get("fenced_rejections", [])
        if fr:
            lines.append(f"fenced-write rejections: {len(fr)}")
            for f in fr[:8]:
                lines.append(
                    f"  {f['api']} under {f['lease']}@{f['token']} "
                    f"(current {f['current']})"
                )
        return "\n".join(lines)

    # -- serialization (/debug/flight + sim --flight-out) ------------------
    def snapshot(self) -> dict:
        data = {
            "schema": SNAPSHOT_SCHEMA,
            "kind": "flight-snapshot",
            "ledger": self.ledger.snapshot(),
            "ownership_timeline": [
                [t, list(key), prev, cur, token]
                for t, key, prev, cur, token in self.ownership_timeline
            ],
            "adoptions": self.adoptions(),
            "rebalances": self.rebalances(),
            "fenced_rejections": self.fenced_rejections(),
            "bound_uids": self.bound_uids(),
            "coverage": self.coverage(),
        }
        if self.audit is not None and hasattr(self.audit, "tail"):
            data["audit"] = [
                r.as_dict() for r in self.audit.tail(RECORDS_CAP)
            ]
        if self.events is not None and hasattr(self.events, "query"):
            data["events"] = [
                {
                    "kind": e.kind, "name": e.name, "type": e.type,
                    "reason": e.reason, "message": e.message,
                    "at": round(e.at, 3), "count": e.count,
                }
                for e in self.events.query()[-RECORDS_CAP:]
            ]
        return data

    def save(self, path: str) -> str:
        import json

        with open(path, "w") as f:
            json.dump(self.snapshot(), f, indent=1, sort_keys=True)
            f.write("\n")
        return path

    @classmethod
    def from_snapshot(cls, data: dict) -> "FleetRecorder":
        from .audit import AuditRecord

        audit = [
            AuditRecord.from_dict(d) for d in data.get("audit", [])
        ] or None
        # event DICTS, the shape obs.explain's offline branch consumes
        events = data.get("events") or None
        return cls(
            ledger=CorrelationLedger.from_snapshot(data.get("ledger", {})),
            audit=audit,
            events=events,
            ownership_timeline=[
                (t, tuple(key), prev, cur, token)
                for t, key, prev, cur, token in data.get(
                    "ownership_timeline", ()
                )
            ],
            adoptions=data.get("adoptions", ()),
            rebalances=data.get("rebalances", ()),
            fenced_rejections=data.get("fenced_rejections", ()),
            bound_uids=data.get("bound_uids", ()),
        )

    @classmethod
    def load(cls, path: str) -> "FleetRecorder":
        import json

        with open(path) as f:
            return cls.from_snapshot(json.load(f))
