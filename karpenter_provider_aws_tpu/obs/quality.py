"""Solver-quality telemetry: was the fast answer also a good one?

Two layers:

 - **In-band (every solve, cheap):** ``solve_quality`` computes packing
   efficiency (requested/allocatable per resource across committed
   launches) and the unschedulable rate from the finished ``SolveResult``
   alone — O(specs + pods), stamped into the solve's
   ``ProvenanceRecord.quality`` and exported as gauges.

 - **Sampled (off the hot path):** ``OracleSampler`` replays the pending
   set through the pure-numpy FFD oracle (``scheduling/oracle.py``) and
   publishes ``karpenter_solver_cost_vs_oracle`` — committed cost over the
   oracle's cost. Sampling is keyed on the cluster ``(epoch, rev)`` token:
   an unchanged pass NEVER re-runs the oracle (the <1ms warm-pass
   contract), and pure-launch passes only (binds to existing capacity
   make the all-new-nodes oracle incomparable). ``KARPENTER_TPU_ORACLE_SAMPLE=0``
   disables outright.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import numpy as np

log = logging.getLogger("karpenter.tpu.obs")


def packing_efficiency(requested: np.ndarray, allocatable: np.ndarray) -> dict:
    """Per-resource requested/allocatable for the resources that exist on
    both sides (cpu/memory always; accelerators when present)."""
    from ..models.resources import RESOURCE_AXES

    out: dict[str, float] = {}
    for i, name in enumerate(RESOURCE_AXES):
        if allocatable[i] > 0 and requested[i] > 0:
            out[name] = round(float(requested[i] / allocatable[i]), 4)
    return out


# Resources each packing gauge has ever reported: a resource that leaves
# the efficiency map (cluster emptied, workload shape changed) is zeroed
# rather than left frozen at its last value — a dashboard reading a
# packing gauge must never see a dead number.
_reported: dict[int, set] = {}


def _set_packing_gauges(gauge, eff: dict) -> None:
    seen = _reported.setdefault(id(gauge), set())
    for resource in seen - set(eff):
        gauge.set(0.0, resource=resource)
    for resource, v in eff.items():
        gauge.set(v, resource=resource)
    seen |= set(eff)


def solve_quality(result, catalog) -> dict:
    """Compute + export the in-band quality block for one SolveResult.
    Cheap and exception-safe: quality must never take down the solve."""
    from ..metrics import SOLVE_PACKING_EFFICIENCY, UNSCHEDULABLE_PODS
    from ..models.resources import NUM_RESOURCES

    quality: dict = {}
    try:
        requested = np.zeros(NUM_RESOURCES, dtype=np.float64)
        allocatable = np.zeros(NUM_RESOURCES, dtype=np.float64)
        for spec in result.node_specs:
            it = catalog.get(spec.instance_type_options[0]) if spec.instance_type_options else None
            if it is not None:
                allocatable += np.asarray(it.capacity().v, dtype=np.float64)
            for pod in spec.pods:
                requested += np.asarray(pod.requests.v, dtype=np.float64)
        if result.node_specs and allocatable.any():
            eff = packing_efficiency(requested, allocatable)
            _set_packing_gauges(SOLVE_PACKING_EFFICIENCY, eff)
            if eff:
                quality["packing_efficiency"] = eff
        n_unsched = len(result.unschedulable)
        if n_unsched:
            UNSCHEDULABLE_PODS.inc(n_unsched)
        if result.num_pods:
            quality["unschedulable_rate"] = round(n_unsched / result.num_pods, 4)
        prov = result.provenance
        if prov is not None and prov.fallback:
            quality["fallback"] = prov.fallback
        if prov is not None and quality:
            prov.quality.update(quality)
    except Exception:  # pragma: no cover - defensive
        log.exception("solve quality telemetry failed")
    return quality


class OracleSampler:
    """Price-optimality gap vs the FFD oracle, sampled off the hot path."""

    def __init__(self):
        self._last_key: Optional[tuple] = None

    def maybe_sample(
        self, cluster, result, pods, nodepools, catalog,
        occupancy=None, type_allow=None, reserved_allow=None,
        nodeclass_by_pool=None, revision=None,
    ) -> Optional[float]:
        """Returns the gap (committed/oracle) when sampled, else None.

        Skips when: disabled, the cluster ``(epoch, rev)`` is unchanged
        since the last sample (identical passes pay nothing), the plan
        binds to existing capacity (oracle incomparable), nothing
        launched, or more than one nodepool competed (the oracle is
        single-pool)."""
        if os.environ.get("KARPENTER_TPU_ORACLE_SAMPLE", "1") != "1":
            return None
        key = (
            getattr(cluster, "epoch", None),
            getattr(cluster, "rev", None),
        )
        if key == self._last_key:
            return None
        self._last_key = key
        if result.binds or not result.node_specs or len(nodepools) != 1:
            return None
        try:
            from ..ops.encode import encode_problem
            from ..scheduling.oracle import ffd_oracle, oracle_cost

            pool = nodepools[0]
            # same arguments as the solve's own encode, so the revision-
            # keyed problem cache almost always serves this for free
            problem = encode_problem(
                pods, catalog, nodepool=pool, occupancy=occupancy,
                allowed_types=(type_allow or {}).get(pool.name),
                allow_reserved=(
                    reserved_allow.get(pool.name, False)
                    if reserved_allow is not None else True
                ),
                nodeclass=(nodeclass_by_pool or {}).get(pool.name),
                revision=revision,
            )
            nodes, _unplaced = ffd_oracle(problem)
            base = oracle_cost(nodes)
            if base <= 0:
                return None
            gap = float(result.total_cost) / base
            from ..metrics import SOLVE_COST_VS_ORACLE

            SOLVE_COST_VS_ORACLE.set(gap)
            if result.provenance is not None:
                result.provenance.quality["cost_vs_oracle"] = round(gap, 4)
            return gap
        except Exception:  # pragma: no cover - defensive
            log.exception("oracle quality sample failed")
            return None


_last_pack: tuple = (None, None)  # (weakref to the last ct, its efficiency)


def cluster_packing(ct) -> dict:
    """Per-resource bound/allocatable across a consolidation snapshot's
    live nodes (``ClusterTensors``) — the cluster-wide packing SLI the
    screen sweep refreshes each pass. O(N x R) numpy sums, memoized on
    tensor identity: a no-change warm pass serves the SAME ClusterTensors
    object (ops/encode_delta.py contract), so it pays a pointer compare
    here, keeping the <1ms warm-pass budget intact."""
    global _last_pack
    import weakref

    from ..metrics import CLUSTER_PACKING_EFFICIENCY

    ref, cached = _last_pack
    if ref is not None and ref() is ct:
        return cached
    used = np.asarray(ct.used_total, dtype=np.float64).sum(axis=0)
    cap = used + np.asarray(ct.free, dtype=np.float64).sum(axis=0)
    eff = packing_efficiency(used, cap)
    _set_packing_gauges(CLUSTER_PACKING_EFFICIENCY, eff)
    try:
        _last_pack = (weakref.ref(ct), eff)
    except TypeError:  # pragma: no cover - non-weakrefable snapshot
        _last_pack = (None, None)
    return eff
