"""Solver-quality telemetry: was the fast answer also a good one?

Two layers:

 - **In-band (every solve, cheap):** ``solve_quality`` computes packing
   efficiency (requested/allocatable per resource across committed
   launches) and the unschedulable rate from the finished ``SolveResult``
   alone — O(specs + pods), stamped into the solve's
   ``ProvenanceRecord.quality`` and exported as gauges.

 - **Sampled (off the hot path):** ``OracleSampler`` replays the pending
   set through the pure-numpy FFD oracle (``scheduling/oracle.py``) — one
   weight-ordered pool sweep with fall-through, mirroring the solver's
   multi-nodepool walk — and publishes
   ``karpenter_solver_cost_vs_oracle``: committed cost over the oracle's
   cost. Sampling is keyed on the cluster ``(epoch, rev)`` token: an
   unchanged pass NEVER re-runs the oracle (the <1ms warm-pass contract),
   and pure-launch passes only (binds to existing capacity make the
   all-new-nodes oracle incomparable). With the optimizer lane adopted,
   the sampled gap drops BELOW 1.0 — the witness that the global plan
   beat the greedy. ``KARPENTER_TPU_ORACLE_SAMPLE=0`` disables outright.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import numpy as np

log = logging.getLogger("karpenter.tpu.obs")


def packing_efficiency(requested: np.ndarray, allocatable: np.ndarray) -> dict:
    """Per-resource requested/allocatable for the resources that exist on
    both sides (cpu/memory always; accelerators when present)."""
    from ..models.resources import RESOURCE_AXES

    out: dict[str, float] = {}
    for i, name in enumerate(RESOURCE_AXES):
        if allocatable[i] > 0 and requested[i] > 0:
            out[name] = round(float(requested[i] / allocatable[i]), 4)
    return out


# Resources each packing gauge has ever reported: a resource that leaves
# the efficiency map (cluster emptied, workload shape changed) is zeroed
# rather than left frozen at its last value — a dashboard reading a
# packing gauge must never see a dead number.
_reported: dict[int, set] = {}


def _set_packing_gauges(gauge, eff: dict) -> None:
    seen = _reported.setdefault(id(gauge), set())
    for resource in seen - set(eff):
        gauge.set(0.0, resource=resource)
    for resource, v in eff.items():
        gauge.set(v, resource=resource)
    seen |= set(eff)


def solve_quality(result, catalog) -> dict:
    """Compute + export the in-band quality block for one SolveResult.
    Cheap and exception-safe: quality must never take down the solve."""
    from ..metrics import SOLVE_PACKING_EFFICIENCY, UNSCHEDULABLE_PODS
    from ..models.resources import NUM_RESOURCES

    quality: dict = {}
    try:
        requested = np.zeros(NUM_RESOURCES, dtype=np.float64)
        allocatable = np.zeros(NUM_RESOURCES, dtype=np.float64)
        for spec in result.node_specs:
            it = catalog.get(spec.instance_type_options[0]) if spec.instance_type_options else None
            if it is not None:
                allocatable += np.asarray(it.capacity().v, dtype=np.float64)
            for pod in spec.pods:
                requested += np.asarray(pod.requests.v, dtype=np.float64)
        if result.node_specs and allocatable.any():
            eff = packing_efficiency(requested, allocatable)
            _set_packing_gauges(SOLVE_PACKING_EFFICIENCY, eff)
            if eff:
                quality["packing_efficiency"] = eff
        n_unsched = len(result.unschedulable)
        if n_unsched:
            UNSCHEDULABLE_PODS.inc(n_unsched)
        if result.num_pods:
            quality["unschedulable_rate"] = round(n_unsched / result.num_pods, 4)
        prov = result.provenance
        if prov is not None and prov.fallback:
            quality["fallback"] = prov.fallback
        if prov is not None and quality:
            prov.quality.update(quality)
    except Exception:  # pragma: no cover - defensive
        log.exception("solve quality telemetry failed")
    return quality


class OracleSampler:
    """Price-optimality gap vs the FFD oracle, sampled off the hot path.

    Multi-pool aware: the oracle replays the SAME weight-ordered pool
    sweep the solver runs (pods a pool's oracle cannot place fall through
    to the next pool), so ``cost_vs_oracle`` measures exactly the
    pure-launch passes the optimizer lane targets — single-pool floods
    AND the multi-pool mixed fleets where fragmentation money lives."""

    def __init__(self):
        self._last_key: Optional[tuple] = None

    def maybe_sample(
        self, cluster, result, pods, nodepools, catalog,
        occupancy=None, type_allow=None, reserved_allow=None,
        nodeclass_by_pool=None, revision=None,
    ) -> Optional[float]:
        """Returns the gap (committed/oracle) when sampled, else None.

        Skips when: disabled, the cluster ``(epoch, rev)`` is unchanged
        since the last sample (identical passes pay nothing), the plan
        binds to existing capacity (oracle incomparable), or nothing
        launched. Pool order and fall-through mirror
        ``scheduling.solver._solve_multi_nodepool``; the per-pool encode
        hits the revision-keyed problem cache for the first pool, so a
        single-pool sample stays as cheap as it was."""
        if os.environ.get("KARPENTER_TPU_ORACLE_SAMPLE", "1") != "1":
            return None
        key = (
            getattr(cluster, "epoch", None),
            getattr(cluster, "rev", None),
        )
        if key == self._last_key:
            return None
        self._last_key = key
        if result.binds or not result.node_specs or not nodepools:
            return None
        try:
            from ..ops.encode import encode_problem
            from ..scheduling.oracle import ffd_oracle, oracle_cost

            base = 0.0
            remaining = list(pods)
            first = True
            for pool in sorted(nodepools, key=lambda p: -p.weight):
                if not remaining:
                    break
                # First pool: same arguments as the solve's own encode, so
                # the revision-keyed problem cache serves it free. LATER
                # pools get revision=None — the fall-through pod list is
                # NOT a pure function of the revision (the cache contract,
                # ops/encode.py: the revision path collapses the pods key
                # to (rev, len, id(first))), and the solver's own chained
                # pool problems could collide with it.
                problem = encode_problem(
                    remaining, catalog, nodepool=pool, occupancy=occupancy,
                    allowed_types=(type_allow or {}).get(pool.name),
                    allow_reserved=(
                        reserved_allow.get(pool.name, False)
                        if reserved_allow is not None else True
                    ),
                    nodeclass=(nodeclass_by_pool or {}).get(pool.name),
                    revision=revision if first else None,
                )
                first = False
                nodes, unplaced = ffd_oracle(problem)
                base += oracle_cost(nodes)
                # fall-through: unencodable pods + each group's unplaced
                # tail ride to the next pool, like the solver's pool sweep
                leftover = [p for p, _why in problem.unencodable]
                for g, cnt in unplaced.items():
                    plist = problem.group_pods[g]
                    if problem.atomic is not None and problem.atomic[g]:
                        leftover.extend(plist)
                    else:
                        leftover.extend(plist[len(plist) - cnt:])
                remaining = leftover
            if base <= 0:
                return None
            gap = float(result.total_cost) / base
            from ..metrics import SOLVE_COST_VS_ORACLE

            SOLVE_COST_VS_ORACLE.set(gap)
            if result.provenance is not None:
                result.provenance.quality["cost_vs_oracle"] = round(gap, 4)
            return gap
        except Exception:  # pragma: no cover - defensive
            log.exception("oracle quality sample failed")
            return None


_last_pack: tuple = (None, None)  # (weakref to the last ct, its efficiency)


def cluster_packing(ct) -> dict:
    """Per-resource bound/allocatable across a consolidation snapshot's
    live nodes (``ClusterTensors``) — the cluster-wide packing SLI the
    screen sweep refreshes each pass. O(N x R) numpy sums, memoized on
    tensor identity: a no-change warm pass serves the SAME ClusterTensors
    object (ops/encode_delta.py contract), so it pays a pointer compare
    here, keeping the <1ms warm-pass budget intact."""
    global _last_pack
    import weakref

    from ..metrics import CLUSTER_PACKING_EFFICIENCY

    ref, cached = _last_pack
    if ref is not None and ref() is ct:
        return cached
    used = np.asarray(ct.used_total, dtype=np.float64).sum(axis=0)
    cap = used + np.asarray(ct.free, dtype=np.float64).sum(axis=0)
    eff = packing_efficiency(used, cap)
    _set_packing_gauges(CLUSTER_PACKING_EFFICIENCY, eff)
    try:
        _last_pack = (weakref.ref(ct), eff)
    except TypeError:  # pragma: no cover - non-weakrefable snapshot
        _last_pack = (None, None)
    return eff


def fleet_hourly_cost(cluster, catalog) -> float:
    """Total $/hr of the live fleet: every node priced by its instance
    type and capacity type from the catalog's pricing model. The number
    behind the multi-replica packing-envelope-parity check (a sharded
    provisioning split must not buy a measurably more expensive fleet
    than the single-replica solve would have) — deterministic given the
    store and the static catalog."""
    total = 0.0
    for node in cluster.snapshot_nodes():
        it = catalog.get(node.instance_type())
        if it is None:
            continue
        try:
            if node.capacity_type() == "spot":
                total += float(catalog.pricing.spot_price(it, node.zone()))
            else:
                total += float(catalog.pricing.on_demand_price(it))
        except Exception:  # pragma: no cover - defensive
            continue
    return round(total, 4)
