"""Decision audit log: a bounded, thread-safe ring of structured records.

Every consequential control-plane decision appends one JSON-ready record:
pod placements (winning instance type + price + the top rejected
alternatives), consolidation accept/reject with hourly savings,
interruption drains, evictions, and lifecycle reaps. The ring answers
"why did the controller decide X" after the fact — the judgment-layer
complement to trace/ (which answers "what ran and how long").

Append is O(1) (``deque.append`` under one lock) and the ring is bounded
(``capacity``), so a controller loop running for weeks can never grow
memory through the audit plane. Records are plain data; ``to_jsonl`` /
``load_jsonl`` round-trip them for the ``obs explain`` CLI.
"""

from __future__ import annotations

import itertools
import json
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

_seq = itertools.count(1)

# Well-known record kinds (free-form strings are allowed; these are what
# the shipped controllers emit and what /debug/decisions groups by).
PLACEMENT = "placement"
DISRUPTION = "disruption"
INTERRUPTION = "interruption"
EVICTION = "eviction"
LIFECYCLE = "lifecycle"


@dataclass(frozen=True)
class AuditRecord:
    seq: int                 # process-unique, monotonic
    at: float                # store-clock timestamp of the decision
    kind: str                # placement | disruption | interruption | ...
    subject_kind: str        # Pod | NodeClaim | Node | NodePool | SLO
    subject: str             # object name
    decision: str            # machine key: launch:<type> | bind:<node> | ...
    detail: dict = field(default_factory=dict)
    rev: Optional[int] = None  # cluster revision at decision time

    def as_dict(self) -> dict:
        d = {
            "seq": self.seq,
            "at": round(float(self.at), 3),
            "kind": self.kind,
            "subject_kind": self.subject_kind,
            "subject": self.subject,
            "decision": self.decision,
            "detail": dict(self.detail),
        }
        if self.rev is not None:
            d["rev"] = int(self.rev)
        return d

    @staticmethod
    def from_dict(d: dict) -> "AuditRecord":
        return AuditRecord(
            seq=int(d.get("seq", 0)),
            at=float(d.get("at", 0.0)),
            kind=str(d.get("kind", "")),
            subject_kind=str(d.get("subject_kind", "")),
            subject=str(d.get("subject", "")),
            decision=str(d.get("decision", "")),
            detail=dict(d.get("detail") or {}),
            rev=d.get("rev"),
        )


class AuditLog:
    """Bounded thread-safe decision ring. One per environment (hermetic
    tests own theirs); the process default backs the CLI and operator."""

    def __init__(self, capacity: int = 8192, clock=None):
        self.clock = clock
        self._lock = threading.Lock()
        self._ring: deque[AuditRecord] = deque(maxlen=capacity)

    def _now(self) -> float:
        if self.clock is not None:
            return self.clock.now()
        import time

        return time.monotonic()

    def record(
        self,
        kind: str,
        subject_kind: str,
        subject: str,
        decision: str,
        detail: Optional[dict] = None,
        at: Optional[float] = None,
        rev: Optional[int] = None,
    ) -> AuditRecord:
        rec = AuditRecord(
            seq=next(_seq),
            at=self._now() if at is None else at,
            kind=kind,
            subject_kind=subject_kind,
            subject=subject,
            decision=decision,
            detail=detail or {},
            rev=rev,
        )
        with self._lock:
            self._ring.append(rec)
        try:
            from ..metrics import AUDIT_RECORDS

            AUDIT_RECORDS.inc(kind=kind)
        except Exception:
            pass
        return rec

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def query(
        self,
        kind: Optional[str] = None,
        subject_kind: Optional[str] = None,
        subject: Optional[str] = None,
        decision_prefix: Optional[str] = None,
        limit: Optional[int] = None,
    ) -> list[AuditRecord]:
        """Filtered records, oldest first. Every non-None filter must
        match; ``limit`` keeps the NEWEST matches."""
        with self._lock:
            out = list(self._ring)
        if kind is not None:
            out = [r for r in out if r.kind == kind]
        if subject_kind is not None:
            out = [r for r in out if r.subject_kind == subject_kind]
        if subject is not None:
            out = [r for r in out if r.subject == subject]
        if decision_prefix is not None:
            out = [r for r in out if r.decision.startswith(decision_prefix)]
        if limit is not None and len(out) > limit:
            out = out[-limit:]
        return out

    def tail(self, n: int = 100) -> list[AuditRecord]:
        with self._lock:
            out = list(self._ring)
        return out[-n:]

    def to_jsonl(self) -> str:
        return "".join(json.dumps(r.as_dict()) + "\n" for r in self.tail(10**9))

    def dump(self, path: str) -> int:
        """Write the ring as JSONL; returns the record count."""
        records = self.tail(10**9)
        with open(path, "w") as f:
            for r in records:
                f.write(json.dumps(r.as_dict()) + "\n")
        return len(records)

    @staticmethod
    def load_jsonl(path: str) -> list[AuditRecord]:
        out: list[AuditRecord] = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(AuditRecord.from_dict(json.loads(line)))
                except (json.JSONDecodeError, TypeError, ValueError):
                    continue  # a torn tail line must not sink the query
        return out

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()


_default = AuditLog()


def default_audit() -> AuditLog:
    return _default
