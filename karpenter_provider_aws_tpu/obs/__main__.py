"""CLI: ``python -m karpenter_provider_aws_tpu.obs explain <kind>/<name>``.

Joins the decision audit log with events and trace provenance for one
object. ``--audit-file`` reads a JSONL ring dumped by ``AuditLog.dump``
(the offline mode operators use against a collected artifact); without it
the process-default audit log is consulted (useful in-process, mostly
empty from a cold CLI). ``slo`` prints the engine's spec table.

``fleet`` is the cross-replica flight recorder's surface
(designs/fleet-flight-recorder.md): ``fleet explain pod/<name>`` prints
the MERGED decision timeline (route -> steal -> solve -> fenced launch ->
bind, whichever replicas performed each hop), ``fleet timeline`` the
partition-ownership Gantt, ``fleet coverage`` the correlation-coverage
stats the smoke gate thresholds. All three read a flight snapshot —
``sim run --flight-out f.json`` or a collected ``/debug/flight`` page —
via ``--flight-file``.
"""

from __future__ import annotations

import argparse
import json
import sys

from .audit import AuditLog, default_audit
from .explain import explain, render_text
from .slo import default_slos


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m karpenter_provider_aws_tpu.obs",
        description="observability toolbox: decision explain + SLO specs "
                    "+ fleet flight recorder",
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_fleet = sub.add_parser(
        "fleet", help="cross-replica flight recorder: merged timelines, "
                      "ownership Gantt, correlation coverage",
    )
    fleet_sub = p_fleet.add_subparsers(dest="fleet_cmd", required=True)
    pf_explain = fleet_sub.add_parser(
        "explain", help="merged cross-replica lifecycle for one object"
    )
    pf_explain.add_argument(
        "subject", help="object as <kind>/<name> (kind is case-insensitive: "
                        "pod/web-0 or Pod/web-0)",
    )
    pf_timeline = fleet_sub.add_parser(
        "timeline", help="partition-ownership Gantt: holders, handoffs, "
                         "adoptions, fence rejections",
    )
    pf_coverage = fleet_sub.add_parser(
        "coverage", help="correlation coverage over bound pods"
    )
    for p in (pf_explain, pf_timeline, pf_coverage):
        p.add_argument(
            "--flight-file", required=True,
            help="flight snapshot JSON (sim run --flight-out, or a "
                 "collected /debug/flight page)",
        )
        p.add_argument("--json", action="store_true")

    p_device = sub.add_parser(
        "device", help="device-plane observatory: jitwatch ledger table, "
                       "top retracers, residency map",
    )
    p_device.add_argument(
        "--snapshot-file", default="",
        help="saved device snapshot (a collected /debug/device page, or a "
             "fleet report whose wall.device plane is read); default: the "
             "in-process ledger (mostly empty from a cold CLI)",
    )
    p_device.add_argument("--json", action="store_true")

    p_explain = sub.add_parser(
        "explain", help="join audit + events + provenance for one object"
    )
    p_explain.add_argument(
        "subject", help="object as <kind>/<name>, e.g. Pod/web-0 or "
                        "NodeClaim/default-abc12",
    )
    p_explain.add_argument(
        "--audit-file", default="",
        help="JSONL audit dump to query (AuditLog.dump output); default: "
             "the in-process audit ring",
    )
    p_explain.add_argument(
        "--sim-report", default="",
        help="fleet-report JSON artifact (sim/ run --report): join the "
             "decision against the simulated day's audit ring, events, "
             "provenance stamps, and run-level SLO summary",
    )
    p_explain.add_argument(
        "--json", action="store_true", help="emit the joined view as JSON"
    )

    p_why = sub.add_parser(
        "why", help="the why-not engine: decoded constraint attribution "
                    "for one object (why is this pod pending / this gang "
                    "withheld / this consolidation rejected)",
    )
    p_why.add_argument(
        "subject", help="object as <kind>/<name>, e.g. pod/web-0 or "
                        "NodeClaim/default-abc12",
    )
    p_why.add_argument(
        "--audit-file", default="",
        help="JSONL audit dump to query (AuditLog.dump output); default: "
             "the in-process audit ring + live why board",
    )
    p_why.add_argument(
        "--sim-report", default="",
        help="fleet-report JSON artifact (sim run --report): decode the "
             "simulated day's why-stamped audit records",
    )
    p_why.add_argument(
        "--flight-file", default="",
        help="flight snapshot to join the object's cross-replica hops "
             "under the verdict",
    )
    p_why.add_argument("--json", action="store_true")

    p_slo = sub.add_parser("slo", help="print the shipped SLO specs")
    p_slo.add_argument("--json", action="store_true")

    args = parser.parse_args(argv)

    if args.cmd == "device":
        from .device import device_summary, load_snapshot, render_device

        snapshot = (
            load_snapshot(args.snapshot_file) if args.snapshot_file
            else device_summary()
        )
        print(json.dumps(snapshot, indent=2, sort_keys=True, default=str)
              if args.json else render_device(snapshot))
        # a snapshot with no families is an empty observatory — exit 3 so
        # the smoke gate can tell "round-tripped nothing" from success
        families = (snapshot.get("jitwatch") or snapshot).get("families", {})
        return 0 if families else 3

    if args.cmd == "fleet":
        from .fleet import FleetRecorder

        recorder = FleetRecorder.load(args.flight_file)
        if args.fleet_cmd == "coverage":
            cov = recorder.coverage()
            print(json.dumps(cov, indent=2) if args.json else "\n".join(
                f"{k}: {v}" for k, v in cov.items()
            ))
            return 0
        if args.fleet_cmd == "timeline":
            gantt = recorder.ownership_gantt()
            print(json.dumps(gantt, indent=2, sort_keys=True)
                  if args.json else recorder.render_gantt(gantt))
            return 0
        # fleet explain
        if "/" not in args.subject:
            print("subject must be <kind>/<name>", file=sys.stderr)
            return 2
        kind, name = args.subject.split("/", 1)
        kind = {"pod": "Pod", "nodeclaim": "NodeClaim"}.get(
            kind.lower(), kind
        )
        view = recorder.explain(kind, name)
        print(json.dumps(view, indent=2, sort_keys=True)
              if args.json else recorder.render_explain(view))
        return 0 if view.get("hops") else 3

    if args.cmd == "why":
        from .why import render_why, why_view

        if "/" not in args.subject:
            print("subject must be <kind>/<name>", file=sys.stderr)
            return 2
        kind, name = args.subject.split("/", 1)
        kind = {"pod": "Pod", "nodeclaim": "NodeClaim"}.get(
            kind.lower(), kind
        )
        if args.sim_report:
            from .audit import AuditRecord

            with open(args.sim_report) as f:
                report = json.load(f)
            audit = [
                AuditRecord.from_dict(r)
                for r in report.get("virtual", {})
                                .get("audit", {}).get("records", [])
            ]
        elif args.audit_file:
            audit = AuditLog.load_jsonl(args.audit_file)
        else:
            audit = default_audit()
        flight = None
        if args.flight_file:
            from .fleet import FleetRecorder

            flight = FleetRecorder.load(args.flight_file)
        view = why_view(kind, name, audit=audit, flight=flight)
        print(json.dumps(view, indent=2, sort_keys=True)
              if args.json else render_why(view))
        # exit 3 when nothing was retained for the subject, so smoke
        # gates can tell "decoded nothing" from success
        return 0 if (view.get("verdict") or view.get("decisions")) else 3

    if args.cmd == "slo":
        specs = [s.as_dict() for s in default_slos()]
        if args.json:
            print(json.dumps(specs, indent=2))
        else:
            for s in specs:
                print(
                    f"{s['name']}: {s['objective']:.3%} over {s['window_s']:.0f}s"
                    + (
                        f", threshold {s['threshold_s']:.0f}s"
                        if s["threshold_s"] is not None else ""
                    )
                    + f" — {s['description']}"
                )
        return 0

    if "/" not in args.subject:
        print("subject must be <kind>/<name>", file=sys.stderr)
        return 2
    kind, name = args.subject.split("/", 1)
    events = None
    slo = None
    if args.sim_report:
        from .audit import AuditRecord

        with open(args.sim_report) as f:
            report = json.load(f)
        virtual = report.get("virtual", {})
        audit = [
            AuditRecord.from_dict(r)
            for r in virtual.get("audit", {}).get("records", [])
        ]
        events = virtual.get("events", [])
        slo = virtual.get("slo_summary", {})
    elif args.audit_file:
        audit = AuditLog.load_jsonl(args.audit_file)
    else:
        audit = default_audit()
    view = explain(kind, name, audit=audit, recorder=events, slo=slo)
    if args.json:
        print(json.dumps(view, indent=2))
    else:
        print(render_text(view))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
