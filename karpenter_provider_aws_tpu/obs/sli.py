"""Lifecycle SLIs: pod pending->nominated->bound, claim created->ready.

``LifecycleSLI`` is the cluster observer (``state.Cluster.observer``): the
sanctioned mutation surface (apply/bind_pod/unbind_pod/delete) and the
registration/liveness controllers call its hooks, and it turns transitions
into:

 - ``karpenter_pod_scheduling_duration_seconds{phase}`` histograms
   (nominate = pending->nominated, bind = pending->bound),
 - ``karpenter_nodeclaim_lifecycle_duration_seconds{phase}`` histograms
   (launch / register / ready / total),
 - SLI events fed to the SLO engine (pod-time-to-bind,
   nodeclaim-time-to-ready),
 - eviction audit records (one per drained pod — the chaos acceptance
   surface), and
 - bounded raw-duration rings so the bench can report exact p50/p99
   time-to-bind instead of reconstructing percentiles from buckets.

All timestamps are in the cluster store's clock base (FakeClock under
test/chaos — transitions are deterministic per seed). Hooks never call
back into the Cluster: they may run under its lock.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional

SAMPLE_CAP = 4096  # bounded raw-duration history (bench percentile source)


def percentile(samples, q: float):
    """Nearest-rank percentile over raw samples (None when empty) — THE
    percentile used by /debug/cluster and the SLI bench rows, so the two
    can never disagree about the same samples."""
    s = sorted(samples)
    if not s:
        return None
    return round(float(s[min(len(s) - 1, int(q * len(s)))]), 3)


class LifecycleSLI:
    def __init__(self, clock=None, engine=None, audit=None):
        self.clock = clock
        self.engine = engine       # SLOEngine or None
        self.audit = audit         # AuditLog or None
        self._lock = threading.Lock()
        self._pod_pending: dict[str, float] = {}      # uid -> pending-at
        self._pod_name: dict[str, str] = {}           # uid -> name (audit)
        self._claims: dict[str, dict] = {}            # name -> phase times
        self.bind_samples: deque = deque(maxlen=SAMPLE_CAP)   # (uid, seconds)
        self.ready_samples: deque = deque(maxlen=SAMPLE_CAP)  # (claim, seconds)

    def _now(self) -> float:
        if self.clock is not None:
            return self.clock.now()
        import time

        return time.monotonic()

    # -- pod lifecycle -----------------------------------------------------
    def pod_applied(self, pod, now: Optional[float] = None) -> None:
        """First sight of a pending pod starts its scheduling clock;
        re-applies of a tracked pod are no-ops."""
        now = self._now() if now is None else now
        with self._lock:
            self._pod_name[pod.uid] = pod.name
            if pod.node_name:
                # applied already-bound (restored state): nothing to time
                self._pod_pending.pop(pod.uid, None)
            elif pod.uid not in self._pod_pending:
                self._pod_pending[pod.uid] = now

    def pod_nominated(self, uid: str, now: Optional[float] = None) -> None:
        now = self._now() if now is None else now
        with self._lock:
            t0 = self._pod_pending.get(uid)
        if t0 is None:
            return
        from ..metrics import POD_SCHEDULING_SECONDS

        POD_SCHEDULING_SECONDS.observe(max(0.0, now - t0), phase="nominate")

    def pod_bound(self, uid: str, node_name: str, now: Optional[float] = None) -> None:
        now = self._now() if now is None else now
        with self._lock:
            t0 = self._pod_pending.pop(uid, None)
        if t0 is None:
            return
        dur = max(0.0, now - t0)
        from ..metrics import POD_SCHEDULING_SECONDS

        POD_SCHEDULING_SECONDS.observe(dur, phase="bind")
        with self._lock:
            self.bind_samples.append((uid, dur))
        if self.engine is not None:
            self.engine.record_latency("pod-time-to-bind", dur, at=now)

    def pod_unbound(self, uid: str, old_node: str, now: Optional[float] = None) -> None:
        """Eviction/drain: the pod re-enters pending and its scheduling
        clock restarts; one eviction audit record per drained pod."""
        now = self._now() if now is None else now
        with self._lock:
            self._pod_pending[uid] = now
            name = self._pod_name.get(uid, uid)
        if self.audit is not None:
            from .audit import EVICTION

            self.audit.record(
                EVICTION, "Pod", name, f"evict:{old_node or '?'}",
                {"node": old_node, "uid": uid}, at=now,
            )

    def pod_deleted(self, uid: str) -> None:
        with self._lock:
            self._pod_pending.pop(uid, None)
            self._pod_name.pop(uid, None)

    # -- nodeclaim lifecycle -----------------------------------------------
    def claim_applied(self, claim, now: Optional[float] = None) -> None:
        """Tracks created (first sight) and launched (provider id set) —
        both flow through Cluster.apply, so no controller changes needed."""
        now = self._now() if now is None else now
        launched = bool(claim.status.provider_id)
        from ..metrics import NODECLAIM_LIFECYCLE_SECONDS

        with self._lock:
            st = self._claims.get(claim.name)
            if st is None:
                st = self._claims[claim.name] = {"created": now}
            if launched and "launched" not in st:
                st["launched"] = now
                delta = max(0.0, now - st["created"])
            else:
                return
        NODECLAIM_LIFECYCLE_SECONDS.observe(delta, phase="launch")

    def claim_registered(self, claim, now: Optional[float] = None) -> None:
        now = self._now() if now is None else now
        from ..metrics import NODECLAIM_LIFECYCLE_SECONDS

        with self._lock:
            st = self._claims.setdefault(claim.name, {"created": now})
            if "registered" in st:
                return
            st["registered"] = now
            base = st.get("launched", st["created"])
        NODECLAIM_LIFECYCLE_SECONDS.observe(
            max(0.0, now - base), phase="register"
        )

    def claim_ready(self, claim, now: Optional[float] = None) -> None:
        now = self._now() if now is None else now
        from ..metrics import NODECLAIM_LIFECYCLE_SECONDS

        with self._lock:
            st = self._claims.setdefault(claim.name, {"created": now})
            if "ready" in st:
                return
            st["ready"] = now
            base = st.get("registered", st.get("launched", st["created"]))
            total = max(0.0, now - st["created"])
            self.ready_samples.append((claim.name, total))
        NODECLAIM_LIFECYCLE_SECONDS.observe(max(0.0, now - base), phase="ready")
        NODECLAIM_LIFECYCLE_SECONDS.observe(total, phase="total")
        if self.engine is not None:
            self.engine.record_latency("nodeclaim-time-to-ready", total, at=now)

    def claim_reaped(self, claim_name: str, now: Optional[float] = None) -> None:
        """Liveness reap: the claim never became a node — an SLO miss."""
        now = self._now() if now is None else now
        if self.engine is not None:
            self.engine.record_bad("nodeclaim-time-to-ready", at=now)
        with self._lock:
            self._claims.pop(claim_name, None)

    def claim_gone(self, claim_name: str) -> None:
        with self._lock:
            self._claims.pop(claim_name, None)

    # -- introspection -----------------------------------------------------
    def pending_ages(self, now: Optional[float] = None) -> dict[str, float]:
        now = self._now() if now is None else now
        with self._lock:
            return {
                self._pod_name.get(uid, uid): max(0.0, now - t0)
                for uid, t0 in self._pod_pending.items()
            }

    def bind_durations(self) -> list[float]:
        with self._lock:
            return [d for _, d in self.bind_samples]

    def ready_durations(self) -> list[float]:
        with self._lock:
            return [d for _, d in self.ready_samples]

    def reset(self) -> None:
        with self._lock:
            self._pod_pending.clear()
            self._pod_name.clear()
            self._claims.clear()
            self.bind_samples.clear()
            self.ready_samples.clear()
