"""Lifecycle SLIs: pod pending->nominated->bound, claim created->ready.

``LifecycleSLI`` is the cluster observer (``state.Cluster.observer``): the
sanctioned mutation surface (apply/bind_pod/unbind_pod/delete) and the
registration/liveness controllers call its hooks, and it turns transitions
into:

 - ``karpenter_pod_scheduling_duration_seconds{phase}`` histograms
   (nominate = pending->nominated, bind = pending->bound),
 - ``karpenter_nodeclaim_lifecycle_duration_seconds{phase}`` histograms
   (launch / register / ready / total),
 - SLI events fed to the SLO engine (pod-time-to-bind,
   nodeclaim-time-to-ready),
 - eviction audit records (one per drained pod — the chaos acceptance
   surface), and
 - bounded raw-duration rings so the bench can report exact p50/p99
   time-to-bind instead of reconstructing percentiles from buckets.

All timestamps are in the cluster store's clock base (FakeClock under
test/chaos — transitions are deterministic per seed). Hooks never call
back into the Cluster: they may run under its lock.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional

SAMPLE_CAP = 4096  # bounded raw-duration history (bench percentile source)


def percentile(samples, q: float):
    """Nearest-rank percentile over raw samples (None when empty) — THE
    percentile used by /debug/cluster and the SLI bench rows, so the two
    can never disagree about the same samples."""
    s = sorted(samples)
    if not s:
        return None
    return round(float(s[min(len(s) - 1, int(q * len(s)))]), 3)


class LifecycleSLI:
    def __init__(self, clock=None, engine=None, audit=None, ledger=None):
        self.clock = clock
        self.engine = engine       # SLOEngine or None
        self.audit = audit         # AuditLog or None
        self.ledger = ledger       # CorrelationLedger or None (hop mint)
        self._lock = threading.Lock()
        self._pod_pending: dict[str, float] = {}      # uid -> pending-at
        self._pod_name: dict[str, str] = {}           # uid -> name (audit)
        self._claims: dict[str, dict] = {}            # name -> phase times
        # sharded provisioning (GLOBAL work queue): uid -> enqueue time,
        # consumed when the pod's work is claimed/stolen off the queue
        self._pod_enqueued: dict[str, float] = {}
        self.bind_samples: deque = deque(maxlen=SAMPLE_CAP)   # (uid, seconds)
        self.ready_samples: deque = deque(maxlen=SAMPLE_CAP)  # (claim, seconds)
        # queue-wait: enqueue->claim for every GLOBAL pod; steal-wait: the
        # subset whose claim was a STEAL (the GLOBAL holder was dead) —
        # the replica-loss tail the provisioning-4r gate bounds
        self.queue_wait_samples: deque = deque(maxlen=SAMPLE_CAP)
        self.steal_wait_samples: deque = deque(maxlen=SAMPLE_CAP)

    def _now(self) -> float:
        if self.clock is not None:
            return self.clock.now()
        import time

        return time.monotonic()

    def _hop_once(self, kind: str, ident: str, hop_kind: str, key: str = "",
                  name: Optional[str] = None, **kw) -> None:
        """Mint the subject's correlation id and record one idempotent
        hop; never raises (observability must not sink the store)."""
        if self.ledger is None:
            return
        try:
            cid = self.ledger.mint(kind, ident, name=name)
            self.ledger.record_once(
                cid, hop_kind, key=key, subject_kind=kind,
                subject=name or ident, **kw
            )
        except Exception:
            pass

    # -- pod lifecycle -----------------------------------------------------
    def pod_applied(self, pod, now: Optional[float] = None) -> None:
        """First sight of a pending pod starts its scheduling clock AND
        mints its correlation id (the flight recorder's first hop);
        re-applies of a tracked pod are no-ops."""
        now = self._now() if now is None else now
        with self._lock:
            self._pod_name[pod.uid] = pod.name
            if pod.node_name:
                # applied already-bound (restored state): nothing to time
                self._pod_pending.pop(pod.uid, None)
                return
            if pod.uid in self._pod_pending:
                return
            self._pod_pending[pod.uid] = now
        self._hop_once("Pod", pod.uid, "pending", name=pod.name, at=now)

    def pod_nominated(self, uid: str, now: Optional[float] = None,
                      claim: Optional[str] = None) -> None:
        now = self._now() if now is None else now
        with self._lock:
            t0 = self._pod_pending.get(uid)
            name = self._pod_name.get(uid, uid)
        if t0 is None:
            return
        from ..metrics import POD_SCHEDULING_SECONDS

        POD_SCHEDULING_SECONDS.observe(max(0.0, now - t0), phase="nominate")
        self._hop_once(
            "Pod", uid, "nominate", key=claim or "", name=name, at=now,
            detail={"claim": claim} if claim else None,
        )

    # -- sharded provisioning (GLOBAL work queue) --------------------------
    def pod_routed_global(self, uid: str, now: Optional[float] = None) -> None:
        """A pending pod entered the work-stealing GLOBAL queue: start its
        queue-wait clock (idempotent — re-routed pods keep the FIRST
        enqueue time; the SLI measures how long work sat unclaimed)."""
        now = self._now() if now is None else now
        with self._lock:
            self._pod_enqueued.setdefault(uid, now)

    def pod_work_claimed(self, uid: str, now: Optional[float] = None,
                         stolen: bool = False) -> None:
        """The pod's GLOBAL-queue work was claimed (by the GLOBAL holder)
        or stolen (the holder was dead). One queue-wait sample per pod;
        stolen claims feed the steal-wait ring too."""
        now = self._now() if now is None else now
        with self._lock:
            t0 = self._pod_enqueued.pop(uid, None)
            if t0 is None:
                return
            wait = max(0.0, now - t0)
            self.queue_wait_samples.append((uid, wait))
            if stolen:
                self.steal_wait_samples.append((uid, wait))
        from ..metrics import POD_QUEUE_WAIT_SECONDS

        POD_QUEUE_WAIT_SECONDS.observe(
            wait, outcome="stolen" if stolen else "claimed"
        )

    def pod_bound(self, uid: str, node_name: str, now: Optional[float] = None) -> None:
        now = self._now() if now is None else now
        with self._lock:
            t0 = self._pod_pending.pop(uid, None)
            name = self._pod_name.get(uid, uid)
        if t0 is None:
            return
        dur = max(0.0, now - t0)
        from ..metrics import POD_SCHEDULING_SECONDS

        POD_SCHEDULING_SECONDS.observe(dur, phase="bind")
        with self._lock:
            self.bind_samples.append((uid, dur))
        if self.engine is not None:
            self.engine.record_latency("pod-time-to-bind", dur, at=now)
        if self.ledger is not None:
            try:
                # plain record (not once): pod_bound fires exactly once
                # per pending episode, and an evict->rebind onto the SAME
                # node must still appear as a second bind hop. The binder
                # is read off the innermost live reconcile span — three
                # controllers can land a bind (scheduling / registration /
                # provisioning) and the timeline should say which did.
                detail = {"node": node_name, "pending_s": round(dur, 3)}
                from ..trace.spans import TRACER

                cur = TRACER.current()
                if cur is not None and cur.name.startswith("controller."):
                    detail["binder"] = cur.name[len("controller."):]
                self.ledger.record(
                    self.ledger.mint("Pod", uid, name=name), "bind",
                    subject_kind="Pod", subject=name, at=now, detail=detail,
                )
            except Exception:
                pass

    def pod_unbound(self, uid: str, old_node: str, now: Optional[float] = None) -> None:
        """Eviction/drain: the pod re-enters pending and its scheduling
        clock restarts; one eviction audit record per drained pod."""
        now = self._now() if now is None else now
        with self._lock:
            self._pod_pending[uid] = now
            name = self._pod_name.get(uid, uid)
        if self.audit is not None:
            from .audit import EVICTION

            self.audit.record(
                EVICTION, "Pod", name, f"evict:{old_node or '?'}",
                {"node": old_node, "uid": uid}, at=now,
            )
        if self.ledger is not None:
            try:
                # an eviction restarts the lifecycle; record (not once —
                # a pod can be evicted repeatedly) so the merged timeline
                # shows the re-pending edge between two bind hops
                self.ledger.record(
                    self.ledger.mint("Pod", uid, name=name), "evict",
                    subject_kind="Pod", subject=name, at=now,
                    detail={"node": old_node},
                )
            except Exception:
                pass

    def pod_deleted(self, uid: str) -> None:
        with self._lock:
            self._pod_pending.pop(uid, None)
            self._pod_name.pop(uid, None)

    # -- nodeclaim lifecycle -----------------------------------------------
    def claim_applied(self, claim, now: Optional[float] = None) -> None:
        """Tracks created (first sight) and launched (provider id set) —
        both flow through Cluster.apply, so no controller changes needed."""
        now = self._now() if now is None else now
        launched = bool(claim.status.provider_id)
        from ..metrics import NODECLAIM_LIFECYCLE_SECONDS

        with self._lock:
            st = self._claims.get(claim.name)
            if st is None:
                st = self._claims[claim.name] = {"created": now}
            if launched and "launched" not in st:
                st["launched"] = now
                delta = max(0.0, now - st["created"])
            else:
                return
        NODECLAIM_LIFECYCLE_SECONDS.observe(delta, phase="launch")
        self._hop_once("NodeClaim", claim.name, "launched", at=now,
                       detail={"provider_id": claim.status.provider_id})

    def claim_registered(self, claim, now: Optional[float] = None) -> None:
        now = self._now() if now is None else now
        from ..metrics import NODECLAIM_LIFECYCLE_SECONDS

        with self._lock:
            st = self._claims.setdefault(claim.name, {"created": now})
            if "registered" in st:
                return
            st["registered"] = now
            base = st.get("launched", st["created"])
        NODECLAIM_LIFECYCLE_SECONDS.observe(
            max(0.0, now - base), phase="register"
        )
        self._hop_once(
            "NodeClaim", claim.name, "register", at=now,
            detail={"node": claim.status.node_name},
        )

    def claim_ready(self, claim, now: Optional[float] = None) -> None:
        now = self._now() if now is None else now
        from ..metrics import NODECLAIM_LIFECYCLE_SECONDS

        with self._lock:
            st = self._claims.setdefault(claim.name, {"created": now})
            if "ready" in st:
                return
            st["ready"] = now
            base = st.get("registered", st.get("launched", st["created"]))
            total = max(0.0, now - st["created"])
            self.ready_samples.append((claim.name, total))
        NODECLAIM_LIFECYCLE_SECONDS.observe(max(0.0, now - base), phase="ready")
        NODECLAIM_LIFECYCLE_SECONDS.observe(total, phase="total")
        if self.engine is not None:
            self.engine.record_latency("nodeclaim-time-to-ready", total, at=now)
        self._hop_once("NodeClaim", claim.name, "ready", at=now,
                       detail={"total_s": round(total, 3)})

    def claim_reaped(self, claim_name: str, now: Optional[float] = None) -> None:
        """Liveness reap: the claim never became a node — an SLO miss."""
        now = self._now() if now is None else now
        if self.engine is not None:
            self.engine.record_bad("nodeclaim-time-to-ready", at=now)
        with self._lock:
            self._claims.pop(claim_name, None)

    def claim_gone(self, claim_name: str) -> None:
        with self._lock:
            self._claims.pop(claim_name, None)

    # -- introspection -----------------------------------------------------
    def pending_ages(self, now: Optional[float] = None) -> dict[str, float]:
        now = self._now() if now is None else now
        with self._lock:
            return {
                self._pod_name.get(uid, uid): max(0.0, now - t0)
                for uid, t0 in self._pod_pending.items()
            }

    def bind_durations(self) -> list[float]:
        with self._lock:
            return [d for _, d in self.bind_samples]

    def ready_durations(self) -> list[float]:
        with self._lock:
            return [d for _, d in self.ready_samples]

    def queue_wait_durations(self) -> list[float]:
        with self._lock:
            return [d for _, d in self.queue_wait_samples]

    def steal_wait_durations(self) -> list[float]:
        with self._lock:
            return [d for _, d in self.steal_wait_samples]

    def bound_uids(self) -> list[str]:
        """Uids of the pods whose binds this SLI timed — the correlation
        coverage denominator (obs/fleet.py)."""
        with self._lock:
            return [uid for uid, _ in self.bind_samples]

    def reset(self) -> None:
        with self._lock:
            self._pod_pending.clear()
            # _pod_name survives: it is an identity map, not judgment
            # history — a pre-reset pod evicted later (the simulator's
            # ballast) must still narrate under its name, not its uid
            self._claims.clear()
            self._pod_enqueued.clear()
            self.bind_samples.clear()
            self.ready_samples.clear()
            self.queue_wait_samples.clear()
            self.steal_wait_samples.clear()
