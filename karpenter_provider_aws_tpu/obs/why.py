"""The why-not engine: device-side constraint attribution for every
unschedulable pod, withheld gang, and rejected consolidation.

The reference Karpenter's core UX is the scheduling-failure event that
names *why* a pod could not be placed; our tensor solver reproduces the
placement math but — before this plane — dropped pods as bare
"unschedulable". This module closes that gap (designs/why-engine.md):

- ``eliminate_bits`` is a vectorized per-(group, type) **elimination
  bitmask** computed device-side under ``tracked_jit`` (family
  ``why.eliminate``) on the same content-cached tensors the FFD/LP
  programs already hold — zero new link payload. One bit per constraint
  plane the encode can express: resource shape, compat/requirements,
  dark offering window (refined host-side into ICE / market window /
  expired reservation), empty zone window, priced-out row.

- ``attribute`` decodes the bitmasks into ranked human explanations:
  the **nearest-miss** instance type is the one eliminated by the
  FEWEST constraint planes, and its surviving bits name the reasons.
  Dark-offering bits are refined host-side against the ICE cache
  (``catalog.unavailable``) and the market plane's reservation windows
  (``market/offerings.py``), and the chaos harness's ambient fault
  context upgrades bare ``capacity`` verdicts inside a price-spike
  window to ``market:price-spike``.

- The decoded tokens ride four channels, all gated on the
  ``KARPENTER_TPU_WHY=0`` kill switch so the lane-off path stays
  byte-identical: ``SolveResult.why`` (per-pod records),
  ``ProvenanceRecord.why`` (per-solve histogram), audit-record detail
  (``detail["why"]`` at the provisioning / disruption stamp sites), and
  the ``karpenter_unschedulable_reason_total`` /
  ``karpenter_consolidation_rejected_total`` metric families.

- ``gang_shortfall`` is the ONE source of truth for the all-or-nothing
  withhold string: ``enforce_gangs`` renders its reason through it, so
  the free-text surface and the bitmask decode can never drift apart
  (pinned by tests/test_gangs.py).

Axes are ladder-padded (values-move-shapes-don't): the group axis rides
the unschedulable remainder's ladder bucket and the type axis is padded
to the CATALOG's ladder bucket — never the per-problem compacted count,
which varies solve-to-solve and would mint retraces the PR 14
zero-retrace gates forbid. ``warm_why_kernels`` pre-traces the buckets
at fleet build, and the family is manifest-warmed (trace/warmup.py).
"""

from __future__ import annotations

import os
import threading
from typing import Iterable, Mapping, Optional, Sequence

import numpy as np

from ..models import labels as lbl

# -- the constraint planes (one bit each, device-computable) ---------------
BIT_SHAPE = 1          # requests exceed the type's allocatable (never fits)
BIT_REQUIREMENTS = 2   # node labels/taints fail the pod's requirements
BIT_OFFERING = 4       # no live (zone, captype) offering inside the window
BIT_ZONE = 8           # the group's zone/captype window is EMPTY
BIT_PRICE = 16         # row survives but priced unusable (inf)

BIT_NAMES = {
    BIT_SHAPE: "shape",
    BIT_REQUIREMENTS: "requirements",
    BIT_OFFERING: "offering-dark",
    BIT_ZONE: "zone",
    BIT_PRICE: "priced-out",
}

# -- the decoded reason vocabulary (metric label values) -------------------
TOKEN_CAPACITY = "capacity"
TOKEN_SHAPE = "shape"
TOKEN_REQUIREMENTS = "requirements"
TOKEN_ZONE = "zone"
TOKEN_HOSTNAME = "hostname"
TOKEN_ICE = "ice"
TOKEN_LIMITS = "limits"
TOKEN_MARKET_CLOSED = "market:window-closed"
TOKEN_MARKET_SPIKE = "market:price-spike"
TOKEN_RESERVATION_EXPIRED = "reservation:expired"
TOKEN_GANG = "gang:atomicity-shortfall"


def enabled() -> bool:
    """The why plane's kill switch. ``KARPENTER_TPU_WHY=0`` disables every
    stamp channel at once — result/provenance/audit/metrics — so the
    legacy path is byte-identical (tested in tests/test_why.py)."""
    return os.environ.get("KARPENTER_TPU_WHY", "1") != "0"


def _ladder(n: int, minimum: int = 8) -> int:
    """The solver's {2^k, 1.5*2^k} padding ladder (scheduling/groups.py)."""
    p = minimum
    while True:
        if n <= p:
            return p
        if n <= p * 3 // 2:
            return p * 3 // 2
        p *= 2


# ---------------------------------------------------------------------------
# device kernel
# ---------------------------------------------------------------------------

def _eliminate_impl(requests, capacity, compat, price, group_window, type_window):
    """[GB, TB] int32 elimination bitmask + [GB] usable-type-exists flag.

    Pure shape-stable jnp over the encode's own tensors. The stored
    ``compat`` is the encode's full conjunction (static labels AND live
    offering AND fits), so the pure-label plane is recovered as
    "fits and live yet still incompatible" — live implies the encode's
    offer_any conjunct, leaving static_ok as the only failed term.
    """
    import jax.numpy as jnp

    fits = (requests[:, None, :] <= capacity[None, :, :] + 1e-6).all(-1)
    live = (
        jnp.einsum(
            "gzc,tzc->gt",
            group_window.astype(jnp.float32),
            type_window.astype(jnp.float32),
        )
        > 0
    )
    zone_any = group_window.reshape(group_window.shape[0], -1).any(-1)
    finite = jnp.isfinite(price)
    bits = jnp.where(~fits, BIT_SHAPE, 0)
    bits = bits | jnp.where(fits & live & ~compat, BIT_REQUIREMENTS, 0)
    bits = bits | jnp.where(~live & zone_any[:, None], BIT_OFFERING, 0)
    bits = bits | jnp.where(~zone_any[:, None], BIT_ZONE, 0)
    bits = bits | jnp.where(fits & live & compat & ~finite, BIT_PRICE, 0)
    usable = (fits & live & compat & finite).any(-1)
    return bits.astype(jnp.int32), usable


_eliminate = None
_eliminate_lock = threading.Lock()


def _kernel():
    """Lazy tracked_jit wrapper: obs/ imports must not force jax."""
    global _eliminate
    if _eliminate is None:
        with _eliminate_lock:
            if _eliminate is None:
                from ..trace.jitwatch import tracked_jit

                _eliminate = tracked_jit(family="why.eliminate")(_eliminate_impl)
    return _eliminate


def eliminate_bits(
    problem, group_idx: Sequence[int], catalog_types: int = 0
) -> tuple[np.ndarray, np.ndarray]:
    """Run the elimination kernel over ``group_idx``'s rows of an
    EncodedProblem; returns (bits [n, T], usable [n]) sliced back to the
    problem's real axes.

    The group axis is ladder-padded over the SELECTED rows (the
    unschedulable remainder — small), and the type axis over
    ``max(T, catalog_types)`` so the per-problem type compaction (which
    varies solve to solve) never mints a fresh compile bucket.
    """
    G = len(problem.group_pods)
    T = problem.capacity.shape[0]
    R = problem.capacity.shape[1]
    idx = np.asarray(list(group_idx), dtype=np.int64)
    n = len(idx)
    GB = _ladder(max(n, 1))
    TB = _ladder(max(T, catalog_types, 1))
    Z, C = problem.type_window.shape[1], problem.type_window.shape[2]

    requests = np.zeros((GB, R), dtype=np.float32)
    compat = np.zeros((GB, TB), dtype=bool)
    price = np.full((GB, TB), np.inf, dtype=np.float32)
    group_window = np.zeros((GB, Z, C), dtype=bool)
    capacity = np.zeros((TB, R), dtype=np.float32)
    type_window = np.zeros((TB, Z, C), dtype=bool)
    if n:
        requests[:n] = problem.requests[idx]
        compat[:n, :T] = problem.compat[idx][:, :T]
        price[:n, :T] = problem.price[idx][:, :T]
        group_window[:n] = problem.group_window[idx]
    capacity[:T] = problem.capacity
    type_window[:T] = problem.type_window

    bits, usable = _kernel()(
        requests, capacity, compat, price, group_window, type_window
    )
    return np.asarray(bits)[:n, :T], np.asarray(usable)[:n]


def warm_why_kernels(max_groups: int = 64, catalog_types: int = 32,
                     zones: int = 4, resources: int = 0) -> None:
    """Pre-trace ``why.eliminate`` at every group-axis ladder bucket up to
    ``max_groups`` for the catalog's type bucket, so arming the plane
    mid-run never mints a compile after the jitwatch warmup boundary.
    Idempotent per process (jit caches by shape)."""
    if resources <= 0:
        from ..models.resources import NUM_RESOURCES

        resources = NUM_RESOURCES
    TB = _ladder(max(catalog_types, 1))
    C = lbl.NUM_CAPACITY_TYPES
    sizes, v = [], 8
    while v <= max_groups:
        sizes.append(v)
        if v * 3 // 2 <= max_groups:
            sizes.append(v * 3 // 2)
        v *= 2
    capacity = np.ones((TB, resources), dtype=np.float32)
    type_window = np.ones((TB, zones, C), dtype=bool)
    for GB in sizes:
        _kernel()(
            np.zeros((GB, resources), dtype=np.float32),
            capacity,
            np.ones((GB, TB), dtype=bool),
            np.ones((GB, TB), dtype=np.float32),
            np.ones((GB, zones, C), dtype=bool),
            type_window,
        )


# ---------------------------------------------------------------------------
# host decode
# ---------------------------------------------------------------------------

def _popcount(x: int) -> int:
    return bin(int(x)).count("1")


def _bit_tokens(bits: int) -> list[str]:
    return [name for bit, name in sorted(BIT_NAMES.items()) if bits & bit]


def classify_reason(reason: str) -> Optional[str]:
    """Map a legacy free-text solver reason string onto the token
    vocabulary (the host-side rejects the device kernel never sees)."""
    r = reason or ""
    if "all-or-nothing" in r:
        return TOKEN_GANG
    if "hostname" in r or "co-located group already running" in r:
        return TOKEN_HOSTNAME
    if "anti-affinity" in r or "zone" in r or "skew" in r:
        return TOKEN_ZONE
    if "taints" in r or "requirements" in r or "minValues" in r:
        return TOKEN_REQUIREMENTS
    if "exceed nodepool limits" in r:
        return TOKEN_LIMITS
    if "no instance type fits" in r:
        return None  # the kernel decode is strictly more specific
    return None


def _active_faults() -> str:
    """The ambient fault context (trace/provenance.py providers): the
    fleet simulator registers ``sim_active_faults`` and the chaos harness
    ``chaos_active_faults`` — the decode reads both."""
    try:
        from ..trace import provenance as _prov

        ctx: dict = {}
        for p in list(getattr(_prov, "_ambient_providers", ())):
            try:
                ctx.update(p() or {})
            except Exception:
                continue
        return ",".join((
            str(ctx.get("sim_active_faults", "")),
            str(ctx.get("chaos_active_faults", "")),
        ))
    except Exception:  # pragma: no cover - attribution is best-effort
        return ""


def _refine_dark(problem, g: int, t: int, catalog) -> str:
    """Name WHY the nearest-miss type's offering window is dark: walk the
    group's allowed (zone, captype) cells where the type's window is off
    and classify against the ICE cache and the market plane's reservation
    windows. Falls back to ``zone`` when the group restricted zones, else
    ``capacity`` (every offering genuinely absent)."""
    tname = problem.type_names[t]
    zones = problem.zones
    gw = problem.group_window[g]
    tw = problem.type_window[t]
    windows = None
    now = 0.0
    if catalog is not None:
        try:
            from ..market.offerings import windows_from_reservations

            windows = windows_from_reservations(catalog.reservations.list())
            now = catalog._clock.now()
        except Exception:
            windows = None
    saw_ice = saw_closed = saw_expired = False
    for z in range(gw.shape[0]):
        for c in range(gw.shape[1]):
            if not gw[z, c] or tw[z, c]:
                continue
            zone = zones[z] if z < len(zones) else ""
            captype = lbl.CAPACITY_TYPES[c]
            if catalog is not None and catalog.unavailable.is_unavailable(
                tname, zone, captype
            ):
                saw_ice = True
                continue
            if c == lbl.RESERVED_INDEX and windows:
                from ..market.offerings import dark_cell_reason

                verdict = dark_cell_reason(windows, tname, zone, now)
                if verdict == TOKEN_MARKET_CLOSED:
                    saw_closed = True
                elif verdict == TOKEN_RESERVATION_EXPIRED:
                    saw_expired = True
    if saw_ice:
        return TOKEN_ICE
    if saw_closed:
        return TOKEN_MARKET_CLOSED
    if saw_expired:
        return TOKEN_RESERVATION_EXPIRED
    zone_allowed = problem.group_zone_allowed[g]
    if not zone_allowed.all():
        return TOKEN_ZONE
    return TOKEN_CAPACITY


def attribute(
    pods: Sequence,
    problems: Mapping[str, object],
    catalog=None,
    reasons: Optional[Mapping[str, str]] = None,
    gang_withheld: Optional[Iterable[str]] = None,
) -> dict[str, dict]:
    """Decode elimination bitmasks into per-pod why records.

    ``problems`` maps nodepool name -> the pool's LAST EncodedProblem of
    the solve (stashed by ``_solve_multi_nodepool``); ``reasons`` is the
    solver's legacy uid -> free-text map (host-side rejects win over the
    kernel when they are strictly more specific); ``gang_withheld`` names
    the uids the all-or-nothing gate stripped.

    Returns uid -> {"top", "tokens", "nearest", "pool"} where ``top`` is
    the single ranked verdict, ``tokens`` the full decoded set, and
    ``nearest`` the nearest-miss instance type (fewest elimination bits)
    with its surviving bit names.
    """
    gang_uids = set(gang_withheld or ())
    reasons = reasons or {}
    catalog_types = 0
    if catalog is not None:
        try:
            catalog_types = len(catalog.list())
        except Exception:
            catalog_types = 0

    # uid -> (pool, problem, group) over every stashed pool problem; a pod
    # can appear in several pools — the decode keeps the NEAREST miss.
    locate: dict[str, list[tuple[str, object, int]]] = {}
    kernel_rows: dict[int, tuple[np.ndarray, np.ndarray]] = {}
    prob_list = list(problems.items())
    for pi, (_pool, prob) in enumerate(prob_list):
        for g, plist in enumerate(prob.group_pods):
            for p in plist:
                locate.setdefault(p.uid, []).append((pi, g))

    wanted: dict[int, set[int]] = {}
    for pod in pods:
        for pi, g in locate.get(pod.uid, ()):
            wanted.setdefault(pi, set()).add(g)
    for pi, gset in wanted.items():
        prob = prob_list[pi][1]
        order = sorted(gset)
        bits, usable = eliminate_bits(prob, order, catalog_types)
        kernel_rows[pi] = ({g: i for i, g in enumerate(order)}, (bits, usable))

    spike = "PriceSpike" in _active_faults()
    out: dict[str, dict] = {}
    for pod in pods:
        uid = pod.uid
        tokens: list[str] = []
        nearest: Optional[dict] = None
        pool_name = ""
        legacy = classify_reason(reasons.get(uid, ""))
        if uid in gang_uids or legacy == TOKEN_GANG:
            tokens.append(TOKEN_GANG)
        # nearest miss across every pool that encoded this pod
        best = None  # (popcount, pool, problem, g, t, bits_row, usable)
        for pi, g in locate.get(uid, ()):
            got = kernel_rows.get(pi)
            if got is None:
                continue
            row_of, (bits, usable) = got
            i = row_of.get(g)
            if i is None:
                continue
            row = bits[i]
            if row.size == 0:
                continue
            # 5-plane popcount, vectorized (np.vectorize is a Python loop)
            pops = sum((row >> k) & 1 for k in range(5))
            t = int(np.argmin(pops))
            cand = (int(pops[t]), pi, g, t, row, bool(usable[i]))
            if best is None or cand[0] < best[0]:
                best = cand
        if best is not None:
            _pop, pi, g, t, row, has_usable = best
            pool_name, prob = prob_list[pi]
            bit_val = int(row[t])
            nearest = {
                "type": prob.type_names[t] if t < len(prob.type_names) else "",
                "bits": _bit_tokens(bit_val),
            }
            if has_usable or bit_val == 0:
                # a usable type existed — the scan ran out of room, limits,
                # or rows: the shortfall is capacity, not constraints
                if TOKEN_CAPACITY not in tokens:
                    tokens.append(TOKEN_CAPACITY)
            else:
                for bit, _name in sorted(BIT_NAMES.items()):
                    if not bit_val & bit:
                        continue
                    if bit == BIT_OFFERING:
                        tok = _refine_dark(prob, g, t, catalog)
                    elif bit == BIT_SHAPE:
                        tok = TOKEN_SHAPE
                    elif bit == BIT_REQUIREMENTS:
                        tok = TOKEN_REQUIREMENTS
                    elif bit == BIT_ZONE:
                        tok = TOKEN_ZONE
                    else:
                        tok = TOKEN_MARKET_CLOSED  # priced-out row
                    if tok not in tokens:
                        tokens.append(tok)
        if legacy and legacy not in tokens:
            # host-side reject (taints/limits/hostname) names the plane the
            # kernel could not see; it outranks a generic kernel verdict
            tokens.insert(0 if not (uid in gang_uids) else 1, legacy)
        if not tokens:
            tokens.append(TOKEN_CAPACITY)
        if spike:
            # chaos ambient context: a price-spike window upgrades bare
            # capacity verdicts and annotates everything else — withheld
            # work inside the spike is market-caused, not a fleet shortfall
            if tokens[0] == TOKEN_CAPACITY:
                tokens.insert(0, TOKEN_MARKET_SPIKE)
            elif TOKEN_MARKET_SPIKE not in tokens:
                tokens.append(TOKEN_MARKET_SPIKE)
        rec = {"top": tokens[0], "tokens": tokens}
        if nearest is not None:
            rec["nearest"] = nearest
        if pool_name:
            rec["pool"] = pool_name
        out[uid] = rec
    return out


def summarize(why_map: Mapping[str, Mapping]) -> dict:
    """Per-solve histogram for ProvenanceRecord.why: reason -> count over
    the ``top`` verdicts, plus the attributed total."""
    hist: dict[str, int] = {}
    for rec in why_map.values():
        top = str(rec.get("top", ""))
        if top:
            hist[top] = hist.get(top, 0) + 1
    return {"reasons": dict(sorted(hist.items())), "attributed": len(why_map)}


# ---------------------------------------------------------------------------
# one source of truth for the gang withhold string (satellite 2)
# ---------------------------------------------------------------------------

def gang_shortfall(name: str, placed: int, need: int) -> str:
    """THE all-or-nothing withhold explanation. ``enforce_gangs`` renders
    its free-text reason through this formatter and ``classify_reason``
    maps it back to ``gang:atomicity-shortfall`` — the decode and the
    string can never drift (pinned in tests/test_gangs.py)."""
    return (
        f"gang {name}: only {int(placed)} of {int(need)} outstanding "
        "members placeable; all-or-nothing group withheld"
    )


# ---------------------------------------------------------------------------
# the live board (backs `obs why` and /debug/why)
# ---------------------------------------------------------------------------

class WhyBoard:
    """Bounded newest-wins record of decoded attributions, keyed by pod
    name — the live lookup surface behind ``obs why pod/<name>`` and the
    ``/debug/why`` page. Thread-safe; O(1) per stamp."""

    def __init__(self, cap: int = 1024):
        self._cap = cap
        self._lock = threading.Lock()
        self._records: dict[str, dict] = {}
        self._hist: dict[str, int] = {}

    def stamp(self, name: str, rec: Mapping, at: float = 0.0) -> None:
        entry = dict(rec)
        entry["at"] = float(at)
        with self._lock:
            self._records.pop(name, None)
            self._records[name] = entry
            top = str(entry.get("top", ""))
            if top:
                self._hist[top] = self._hist.get(top, 0) + 1
            while len(self._records) > self._cap:
                self._records.pop(next(iter(self._records)))

    def get(self, name: str) -> Optional[dict]:
        with self._lock:
            rec = self._records.get(name)
            return dict(rec) if rec else None

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "records": {k: dict(v) for k, v in self._records.items()},
                "reasons": dict(sorted(self._hist.items())),
            }

    def reset(self) -> None:
        with self._lock:
            self._records.clear()
            self._hist.clear()


_board = WhyBoard()


def board() -> WhyBoard:
    return _board


def why_view(kind: str, name: str, audit=None, flight=None) -> dict:
    """The ``obs why <kind>/<name>`` join: every why-stamped decision the
    audit plane retains for the subject (unschedulable placements,
    disruption rejects), the live board's newest verdict, and — when a
    flight snapshot is supplied — the object's cross-replica hops, so one
    command answers "why is this pod pending" with the decoded constraint
    planes attached.

    ``audit`` is an AuditLog or a list of AuditRecord (the CLI's
    ``--audit-file`` / ``--sim-report`` modes); ``flight`` a FleetRecorder.
    """
    records = []
    if audit is not None:
        if hasattr(audit, "query"):
            records = audit.query(subject_kind=kind, subject=name)
        else:
            records = [
                r for r in audit
                if r.subject_kind == kind and r.subject == name
            ]
    decisions = []
    verdict = None
    for r in records:
        d = r.as_dict() if hasattr(r, "as_dict") else dict(r)
        entry = {
            "at": d.get("at"),
            "kind": d.get("kind"),
            "decision": d.get("decision"),
            "reason": (d.get("detail") or {}).get("reason", ""),
        }
        why = (d.get("detail") or {}).get("why")
        if why:
            entry["why"] = why
            verdict = why  # newest why-stamped record wins
        decisions.append(entry)
    live = _board.get(name)
    if live is not None:
        verdict = live
    hops = []
    if flight is not None:
        try:
            hops = flight.explain(kind, name).get("hops", [])
        except Exception:
            hops = []
    return {
        "subject": f"{kind}/{name}",
        "verdict": verdict,
        "decisions": decisions,
        "hops": hops,
    }


def render_why(view: Mapping) -> str:
    """Human rendering of a why_view."""
    lines = [f"why {view['subject']}"]
    verdict = view.get("verdict")
    if verdict:
        lines.append(f"  verdict: {verdict.get('top', '?')}")
        tokens = verdict.get("tokens") or []
        if len(tokens) > 1:
            lines.append(f"  contributing: {', '.join(tokens)}")
        nearest = verdict.get("nearest") or {}
        if nearest:
            bits = ", ".join(nearest.get("bits") or []) or "none"
            lines.append(
                f"  nearest miss: {nearest.get('type', '?')} "
                f"(eliminated by: {bits})"
            )
        if verdict.get("pool"):
            lines.append(f"  nodepool: {verdict['pool']}")
    else:
        lines.append("  verdict: (no why-stamped decision retained)")
    decs = view.get("decisions") or []
    if decs:
        lines.append(f"  decisions ({len(decs)}):")
        for d in decs[-20:]:
            why = d.get("why") or {}
            suffix = f"  [why: {why.get('top')}]" if why else ""
            reason = d.get("reason", "")
            reason = f" — {reason}" if reason else ""
            lines.append(
                f"    t={d.get('at')} {d.get('kind')}/{d.get('decision')}"
                f"{reason}{suffix}"
            )
    hops = view.get("hops") or []
    if hops:
        lines.append(f"  flight hops ({len(hops)}):")
        for h in hops[-12:]:
            lines.append(f"    {h}")
    return "\n".join(lines)


def debug_why_page() -> dict:
    """``/debug/why``: the ranked reason histogram plus the newest decoded
    records (newest last — insertion order is stamp order)."""
    snap = _board.snapshot()
    recs = list(snap["records"].items())
    return {
        "reasons": snap["reasons"],
        "records": dict(recs[-64:]),
        "total": len(recs),
    }
