"""obs/: the judgment layer — SLIs, SLOs, decision audit, solver quality.

PR 1's ``trace/`` answers "what ran and how long"; this subsystem answers
"are we meeting our promises" and "why did the controller decide X":

 - :mod:`.sli`     — lifecycle SLIs (pod pending->bound, claim
   created->ready) via the cluster observer
 - :mod:`.slo`     — declarative SLO specs + multi-window burn-rate engine
 - :mod:`.audit`   — bounded JSONL ring of structured decision records
 - :mod:`.quality` — packing efficiency + FFD-oracle price-gap telemetry
 - :mod:`.explain` — the audit/events/provenance join behind
   ``python -m karpenter_provider_aws_tpu.obs explain <kind>/<name>``

``install()`` wires one ``Obs`` bundle to a cluster + recorder and
registers ``/debug/slo``, ``/debug/decisions``, ``/debug/cluster`` on the
metrics HTTP server. ``Obs.tick`` (driven by the liveness loop) evaluates
the SLOs and runs idle housekeeping (event-recorder dedupe sweep).
"""

from __future__ import annotations

from typing import Optional

from ..trace.correlate import CorrelationLedger
from .audit import AuditLog, AuditRecord, default_audit
from .explain import explain, render_text
from .quality import OracleSampler, cluster_packing, solve_quality
from .sentinel import (
    EdgeTrigger,
    RetraceSentinel,
    SteadyStateSentinel,
    detect_cliffs,
)
from .sli import LifecycleSLI, percentile
from .slo import BurnRule, SLOEngine, SLOSpec, default_slos
from .why import WhyBoard, gang_shortfall, warm_why_kernels
from .why import attribute as why_attribute
from .why import board as why_board
from .why import enabled as why_enabled

__all__ = [
    "AuditLog", "AuditRecord", "BurnRule", "CorrelationLedger",
    "EdgeTrigger", "LifecycleSLI", "Obs", "OracleSampler",
    "RetraceSentinel", "SLOEngine", "SLOSpec",
    "SteadyStateSentinel", "WhyBoard", "cluster_packing", "default_audit",
    "default_obs", "default_slos", "detect_cliffs", "explain",
    "gang_shortfall", "install", "percentile", "render_text",
    "solve_quality", "warm_why_kernels", "why_attribute", "why_board",
    "why_enabled",
]


class Obs:
    """One observability bundle: audit ring + SLO engine + lifecycle SLI
    + oracle sampler + correlation ledger + steady-state sentinel,
    sharing a clock and recorder."""

    def __init__(self, clock=None, recorder=None, audit: Optional[AuditLog] = None,
                 specs=None):
        self.clock = clock
        self.recorder = recorder
        self.audit = audit or AuditLog(clock=clock)
        self.slo = SLOEngine(clock=clock, recorder=recorder, specs=specs)
        # cross-replica correlation ledger (trace/correlate.py): the SLI
        # observer mints ids at first sight and controllers thread hops
        # through it (designs/fleet-flight-recorder.md)
        self.ledger = CorrelationLedger(clock=clock)
        self.sli = LifecycleSLI(clock=clock, engine=self.slo, audit=self.audit,
                                ledger=self.ledger)
        self.oracle = OracleSampler()
        # live steady-state regression sentinel (obs/sentinel.py),
        # evaluated on every tick below
        self.sentinel = SteadyStateSentinel(clock=clock, recorder=recorder)
        # device-plane retrace sentinel: the jitwatch ledger's judge
        # (DeviceRetraceStorm when a warmed-up steady state compiles)
        self.retrace = RetraceSentinel(clock=clock, recorder=recorder)
        self.cluster = None  # set by install()

    def tick(self, now: Optional[float] = None) -> dict:
        """One judgment pass (liveness cadence): evaluate every SLO
        (budget gauges, fast-burn Warning events) and run idle
        housekeeping — the event recorder's dedupe sweep happens here
        even when no new events arrive."""
        snapshot = self.slo.evaluate(now=now)
        try:
            self.sentinel.tick(now=now)
        except Exception:
            pass  # judgment must never take down the liveness loop
        try:
            self.retrace.tick(now=now)
        except Exception:
            pass
        if self.recorder is not None:
            try:
                self.recorder.sweep(now=now)
            except Exception:
                pass
        return snapshot

    def cluster_summary(self) -> dict:
        """The /debug/cluster payload: store shape + live SLI readings."""
        c = self.cluster
        if c is None:
            return {"error": "no cluster installed"}
        pending = self.sli.pending_ages()
        binds = self.sli.bind_durations()
        readies = self.sli.ready_durations()
        # store reads under the cluster lock: this runs on the metrics
        # HTTP thread while controllers mutate — an unlocked iteration
        # would intermittently die mid-apply, exactly when operators look
        with c._lock:
            shape = {
                "rev": getattr(c, "rev", None),
                "nodes": len(c.nodes),
                "nodes_ready": sum(1 for n in c.nodes.values() if n.ready),
                "nodeclaims": len(c.nodeclaims),
                "nodeclaims_draining": sum(
                    1 for cl in c.nodeclaims.values() if cl.deleted
                ),
                "pods": len(c.pods),
                "nodepools": len(c.nodepools),
            }
        shape.update({
            "pods_pending": len(pending),
            "oldest_pending_s": (
                round(max(pending.values()), 3) if pending else 0.0
            ),
            "time_to_bind_s": {
                "samples": len(binds), "p50": percentile(binds, 0.50),
                "p99": percentile(binds, 0.99),
            },
            "time_to_ready_s": {
                "samples": len(readies), "p50": percentile(readies, 0.50),
                "p99": percentile(readies, 0.99),
            },
        })
        return shape

    def reset(self) -> None:
        self.audit.reset()
        self.slo.reset()
        self.sli.reset()
        self.ledger.reset()
        self.sentinel.reset()
        self.retrace.reset()
        self.oracle = OracleSampler()
        # the why board is process-global (the stamp sites have no bundle
        # handle); a bundle reset is the "fresh run" boundary, so clear it
        why_board().reset()


def install(cluster=None, recorder=None, clock=None, specs=None,
            register_debug: bool = True) -> Obs:
    """Build an Obs bundle, attach its lifecycle observer to ``cluster``
    (as ``cluster.observer`` — the sanctioned mutation surface calls its
    hooks), and register the /debug pages on the default metrics
    registry. Safe to call per hermetic environment: pages re-bind to the
    newest bundle."""
    bundle = Obs(clock=clock, recorder=recorder, specs=specs)
    if cluster is not None:
        cluster.observer = bundle.sli
        bundle.cluster = cluster
    if register_debug:
        from ..metrics import REGISTRY

        REGISTRY.register_debug_page("/debug/slo", bundle.tick)
        REGISTRY.register_debug_page(
            "/debug/decisions",
            lambda: [r.as_dict() for r in bundle.audit.tail(200)],
        )
        REGISTRY.register_debug_page("/debug/cluster", bundle.cluster_summary)
        # fleet flight recorder surfaces: the serialized per-process
        # flight snapshot (full schema — ledger + audit + events +
        # coverage — so a collected page round-trips straight into
        # FleetRecorder.from_snapshot), and the live sentinel's
        # baseline + findings
        def _flight_snapshot() -> dict:
            from .fleet import FleetRecorder

            return FleetRecorder(
                ledger=bundle.ledger, audit=bundle.audit,
                events=bundle.recorder,
                bound_uids=bundle.sli.bound_uids(),
            ).snapshot()

        REGISTRY.register_debug_page("/debug/flight", _flight_snapshot)
        REGISTRY.register_debug_page(
            "/debug/sentinel", bundle.sentinel.summary
        )
        # the device-plane observatory (obs/device.py): jitwatch ledger,
        # residency map, link/live-byte accounting, retrace findings
        def _device_page() -> dict:
            from .device import device_summary

            return device_summary(retrace_sentinel=bundle.retrace)

        REGISTRY.register_debug_page("/debug/device", _device_page)
        # the why-not engine (obs/why.py): ranked unschedulable-reason
        # histogram + the newest decoded per-pod attributions, plus the
        # consolidation blocked-cause decode over THIS env's cluster
        from .why import debug_why_page

        def _why_page() -> dict:
            page = debug_why_page()
            try:
                from ..ops.consolidate import blocked_summary

                page["consolidation_blocked"] = blocked_summary(cluster)
            except Exception:
                page["consolidation_blocked"] = {}
            return page

        REGISTRY.register_debug_page("/debug/why", _why_page)
    return bundle


_default: Optional[Obs] = None


def default_obs() -> Obs:
    """Process-default bundle (the operator's; tests build their own via
    ``install``). Lazy: importing obs never constructs state."""
    global _default
    if _default is None:
        from ..events import default_recorder

        _default = Obs(recorder=default_recorder(), audit=default_audit())
    return _default
